"""Mesh-serving tests: placement, fair-share tenancy, drain, ring numerics.

Runs on the conftest-forced 8-virtual-device CPU platform (the same mesh
the parallel tests shard over).  Scheduler and tenancy behavior is driven
with injected stub factories — tier-1 never traces ``process_chunk`` here —
and asserted from the engine's counters (placements, per-replica requests,
per-tenant events), not from timing.  The one real-compute case pins the
ring placement's bit-exactness against the single-device engine on the
all-pairs kernel path (the PR 4 invariant, re-pinned THROUGH both serving
engines).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from das_diff_veh_tpu.config import HealthConfig, MeshServeConfig, ServeConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.serve import (FnComputeFactory, ServingEngine,
                                    ShutdownError, serve_in_thread)
from das_diff_veh_tpu.serve.engine import PoisonInputError
from das_diff_veh_tpu.serve.mesh import (RING, AllPairsComputeFactory,
                                         FairQueue, MeshServingEngine,
                                         PlacementPolicy, TenantDrainingError,
                                         TenantQuarantinedError,
                                         TenantQuotaError)


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

SENTINEL = 999.0


def _section(nch, nt, value=1.0):
    return DasSection(np.full((nch, nt), value, np.float32),
                      np.arange(nch, dtype=np.float64) * 8.16,
                      np.arange(nt, dtype=np.float64) / 250.0)


def _wedge_section(nch=8, nt=32, value=1.0):
    sec = _section(nch, nt, value)
    sec.data[0, 0] = SENTINEL
    return sec


class _MarkGate:
    """Blocks compute only for sections whose [0, 0] sample carries the
    sentinel; every other request passes straight through — so one worker
    can be wedged while its peers (and its own later batch members) run."""

    def __init__(self, order=None):
        self.started = threading.Event()
        self.release = threading.Event()
        self.order = order             # optional execution-order sink

    def build(self, bucket):
        def fn(section, valid, state):
            d = np.asarray(section.data)
            if d[0, 0] == SENTINEL:
                self.started.set()
                assert self.release.wait(timeout=30.0)
            if self.order is not None:
                self.order.append(float(d[0, 1]))
            return float(d[:valid[0], :valid[1]].sum()), state
        return fn


def _mesh_engine(replicas=2, buckets=((8, 32),), gate=None, quota=32,
                 poison_after=None, health=None, max_batch=8, max_queue=64):
    serve_cfg = ServeConfig(buckets=buckets, max_batch=max_batch,
                            max_queue=max_queue,
                            default_deadline_ms=600000.0, health=health)
    cfg = MeshServeConfig(serve=serve_cfg, replicas=replicas,
                          tenant_quota=quota,
                          tenant_poison_quarantine=poison_after)
    build = gate.build if gate is not None else _MarkGate().build
    return MeshServingEngine(FnComputeFactory(build, "mesh-test"), cfg).start()


class _FakeReq:
    def __init__(self, tenant, bucket=(8, 32)):
        self.tenant = tenant
        self.bucket = bucket


# --------------------------------------------------------------------------
# placement policy + fair queue units
# --------------------------------------------------------------------------

def test_placement_policy_priority_order():
    """Ring beats sticky beats least-loaded; draining replicas are never
    picked; all-draining with no ring route returns None (the engine
    sheds)."""
    pol = PlacementPolicy(3, ring_min_channels=100)
    free = [False, False, False]
    # 1. ring: channel count at the threshold routes to the ring even for
    #    a sticky session
    assert pol.place(100, "s", [0, 0, 0], free) == RING
    # 2. least-loaded, ties to the lowest index; session "s" pins there
    assert pol.place(10, "s", [5, 2, 2], free).index == 1
    # 3. sticky: "s" stays on 1 even when 2 is now emptier
    assert pol.place(10, "s", [5, 9, 0], free).index == 1
    assert pol.sticky_replica("s") == 1
    # 4. draining replica loses its stickiness at eviction
    assert pol.place(10, None, [3, 0, 1], [False, True, False]).index == 2
    assert pol.evict_replica(1) == 1
    assert pol.sticky_replica("s") is None
    assert pol.place(10, "s", [0, 0, 0], [False, True, False]).index == 0
    # 5. nowhere to go
    assert pol.place(10, None, [0, 0, 0], [True, True, True]) is None


def test_fair_queue_round_robin_and_head_only_poll():
    """Pops rotate over tenants by least-recently-picked (a flood from one
    tenant cannot starve another's next request); the continuous-batch poll
    only considers each tenant's HEAD, preserving per-tenant FIFO."""
    q = FairQueue()
    a1, a2, a3 = _FakeReq("a"), _FakeReq("a"), _FakeReq("a")
    b1, c1 = _FakeReq("b"), _FakeReq("c")
    for r in (a1, a2, a3, b1, c1):
        q.put(r)
    assert [q.get(0.1) for _ in range(5)] == [a1, b1, c1, a2, a3]
    assert q.get(0.01) is None and q.qsize() == 0
    # head-only: tenant a's head is bucket X, so a cannot contribute to a
    # bucket-Y batch even though a2 (bucket Y) is queued behind it
    ax = _FakeReq("a", bucket=("X",))
    ay, by = _FakeReq("a", bucket=("Y",)), _FakeReq("b", bucket=("Y",))
    for r in (ax, ay, by):
        q.put(r)
    assert q.poll_bucket(("Y",)) is by
    assert q.poll_bucket(("Y",)) is None     # a's head still blocks ay
    assert q.get(0.1) is ax
    assert q.poll_bucket(("Y",)) is ay


# --------------------------------------------------------------------------
# mesh engine: round trip, warmup accounting, continuous batching
# --------------------------------------------------------------------------

def test_mesh_round_trip_and_zero_steady_state_misses():
    """Requests complete correctly across replicas; warmup builds one
    program per (bucket, replica) and the steady-state stream performs zero
    fresh cache builds."""
    eng = _mesh_engine(replicas=4, buckets=((8, 32), (16, 64)))
    try:
        futs = [eng.submit(_section(8, 32, float(i))) for i in range(6)]
        futs += [eng.submit(_section(12, 48, 2.0)) for _ in range(3)]
        vals = [f.result(timeout=15) for f in futs]
        assert vals[:6] == [float(i) * 8 * 32 for i in range(6)]
        assert vals[6:] == [2.0 * 12 * 48] * 3
        snap = eng.metrics()
        assert snap["completed"] == 9
        assert snap["warmup_builds"] == 2 * 4       # buckets x replicas
        assert snap["cache_misses"] == 0
        assert sum(snap["placements"].values()) == 9
        assert sum(r["requests"] for r in snap["replicas"].values()) == 9
        assert snap["mesh"]["replicas"] == 4 and not snap["mesh"]["ring"]
    finally:
        eng.close()


def test_mesh_continuous_admission_into_inflight_batch():
    """Same-bucket requests arriving while a replica executes are admitted
    into its open batch slot at the next member boundary — the continuous
    contract holds per mesh worker, not just on the base dispatcher."""
    gate = _MarkGate()
    eng = _mesh_engine(replicas=1, gate=gate)
    try:
        f_head = eng.submit(_wedge_section())
        assert gate.started.wait(timeout=10.0)
        f1 = eng.submit(_section(8, 32, 2.0))
        f2 = eng.submit(_section(8, 32, 3.0))
        deadline = time.monotonic() + 5.0
        while eng._replicas[0].queue.qsize() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        gate.release.set()
        results = {f.result(timeout=15) for f in (f_head, f1, f2)}
        assert results == {float(np.asarray(_wedge_section().data).sum()),
                           2.0 * 8 * 32, 3.0 * 8 * 32}
        snap = eng.metrics()
        assert snap["batch"]["count"] == 1
        assert snap["batch"]["max_occupancy"] == 3
        assert snap["continuous_admitted"] == 2
    finally:
        gate.release.set()
        eng.close()


def test_session_sticky_placement():
    """Consecutive requests of one session execute on ONE replica (state
    threading needs a single worker's execution order); a fresh session is
    free to land elsewhere."""
    eng = _mesh_engine(replicas=4)
    try:
        for i in range(3):
            eng.submit(_section(8, 32, float(i + 1)),
                       session="fiber-A").result(timeout=15)
        snap = eng.metrics()
        per_replica = [r["requests"] for r in snap["replicas"].values()]
        assert sorted(per_replica) == [0, 0, 0, 3]
        assert eng.policy.sticky_replica("default::fiber-A") is not None
    finally:
        eng.close()


# --------------------------------------------------------------------------
# tenancy: quota, fair share, quarantine, drain
# --------------------------------------------------------------------------

def test_tenant_quota_rejection_and_release():
    """Quota counts queued + in-flight; the over-quota submit sheds with
    TenantQuotaError; terminal outcomes return the slots (another tenant is
    untouched throughout)."""
    gate = _MarkGate()
    eng = _mesh_engine(replicas=1, gate=gate, quota=2)
    try:
        f_wedged = eng.submit(_wedge_section(), tenant="noisy")
        assert gate.started.wait(timeout=10.0)
        f_queued = eng.submit(_section(8, 32, 2.0), tenant="noisy")
        with pytest.raises(TenantQuotaError):
            eng.submit(_section(8, 32, 3.0), tenant="noisy")
        # the quota is per tenant, not global
        f_other = eng.submit(_section(8, 32, 4.0), tenant="quiet")
        gate.release.set()
        for f in (f_wedged, f_queued, f_other):
            f.result(timeout=15)
        # slots returned: the tenant can submit again
        assert eng.submit(_section(8, 32, 5.0),
                          tenant="noisy").result(timeout=15) == 5.0 * 8 * 32
        snap = eng.metrics()
        assert snap["shed_quota"] == 1
        assert snap["tenants"]["noisy"]["shed_quota"] == 1
        assert snap["tenants"]["noisy"]["completed"] == 3
        assert snap["tenant_table"]["noisy"]["admitted"] == 0
    finally:
        gate.release.set()
        eng.close()


def test_fair_share_across_tenants():
    """With one tenant's flood queued ahead of another's single request,
    the worker alternates tenants (least-recently-picked round-robin): the
    singleton does not wait out the flood."""
    order = []
    gate = _MarkGate(order=order)
    eng = _mesh_engine(replicas=1, gate=gate)
    order.clear()                      # drop the warmup execution's entry
    try:
        f_head = eng.submit(_wedge_section(value=10.0), tenant="head")
        assert gate.started.wait(timeout=10.0)
        futs = [eng.submit(_section(8, 32, v), tenant="flood")
                for v in (1.0, 2.0, 3.0)]
        futs.append(eng.submit(_section(8, 32, 7.0), tenant="solo"))
        deadline = time.monotonic() + 5.0
        while eng._replicas[0].queue.qsize() < 4:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        gate.release.set()
        f_head.result(timeout=15)
        for f in futs:
            f.result(timeout=15)
        # execution order (by the marker value in data[0, 1]): the head,
        # then flood/solo interleaved — solo's request rides second, not
        # behind the whole flood
        assert order == [10.0, 1.0, 7.0, 2.0, 3.0]
    finally:
        gate.release.set()
        eng.close()


def test_tenant_poison_streak_quarantines_and_release_readmits():
    """poison_after consecutive poison sheds auto-quarantine the tenant
    (even healthy submits shed until released); a healthy admission resets
    the streak, and release_tenant lifts the quarantine."""
    eng = _mesh_engine(replicas=1, poison_after=2,
                       health=HealthConfig(enabled=True))
    rng = np.random.default_rng(7)

    def noisy(poison=False):
        sec = _section(8, 32)
        sec.data[:] = rng.standard_normal((8, 32)).astype(np.float32)
        if poison:
            sec.data[3, 5:20] = np.nan
        return sec

    try:
        # a poison shed then a healthy one: streak resets, no quarantine
        with pytest.raises(PoisonInputError):
            eng.submit(noisy(poison=True), tenant="t")
        eng.submit(noisy(), tenant="t").result(timeout=15)
        # two consecutive poisons cross the threshold
        for _ in range(2):
            with pytest.raises(PoisonInputError):
                eng.submit(noisy(poison=True), tenant="t")
        with pytest.raises(TenantQuarantinedError):
            eng.submit(noisy(), tenant="t")
        assert eng.metrics()["tenant_table"]["t"]["quarantined"]
        eng.release_tenant("t")
        eng.submit(noisy(), tenant="t").result(timeout=15)
        snap = eng.metrics()
        assert snap["shed_quarantined"] == 1
        assert snap["tenants"]["t"]["quarantined"] == 1
        assert snap["tenants"]["t"]["completed"] == 2
    finally:
        eng.close()


def test_tenant_drain_under_load():
    """drain_tenant fails the tenant's queued requests with ShutdownError,
    waits out its in-flight one, drops its sessions, and leaves every other
    tenant untouched; new submits shed TenantDrainingError during the
    drain and re-admit fresh after it."""
    gate = _MarkGate()
    eng = _mesh_engine(replicas=1, gate=gate)
    try:
        # the draining tenant's in-flight request wedges the worker
        f_inflight = eng.submit(_wedge_section(), tenant="evict",
                                session="s-evict")
        assert gate.started.wait(timeout=10.0)
        doomed = [eng.submit(_section(8, 32, 2.0), tenant="evict")
                  for _ in range(2)]
        f_keep = eng.submit(_section(8, 32, 3.0), tenant="keep")
        deadline = time.monotonic() + 5.0
        while eng._replicas[0].queue.qsize() < 3:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # during the drain new submits shed; release the gate from a timer
        # so wait_idle can observe the in-flight request complete
        eng.tenants.start_drain("evict")
        with pytest.raises(TenantDrainingError):
            eng.submit(_section(8, 32), tenant="evict")
        threading.Timer(0.2, gate.release.set).start()
        summary = eng.drain_tenant("evict", timeout=15.0)
        assert summary["queued_failed"] == 2 and summary["idle"]
        for f in doomed:
            with pytest.raises(ShutdownError):
                f.result(timeout=1.0)
        f_inflight.result(timeout=15)            # completed, not killed
        assert f_keep.result(timeout=15) == 3.0 * 8 * 32
        assert eng.sessions.sessions_for("evict") == []
        # the record is gone: the tenant re-admits fresh
        assert "evict" not in eng.metrics()["tenant_table"]
        assert eng.submit(_section(8, 32, 4.0),
                          tenant="evict").result(timeout=15) == 4.0 * 8 * 32
    finally:
        gate.release.set()
        eng.close()


def test_replica_drain_under_load_replaces_queued():
    """drain_replica retires one replica while it is mid-compute: its
    queued requests re-place onto survivors and complete even before the
    wedged batch finishes; stickiness re-pins; the drained worker exits
    once released."""
    gate = _MarkGate()
    eng = _mesh_engine(replicas=2, gate=gate)
    try:
        # pin session to replica 0 (first least-loaded pick), then wedge it
        f_wedged = eng.submit(_wedge_section(), session="s", tenant="t")
        assert gate.started.wait(timeout=10.0)
        assert eng.policy.sticky_replica("t::s") == 0
        queued = [eng.submit(_section(8, 32, float(v)), session="s",
                             tenant="t") for v in (2.0, 3.0)]
        deadline = time.monotonic() + 5.0
        while eng._replicas[0].queue.qsize() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        eng.drain_replica(0, timeout=0.5)        # worker still wedged: the
        # queued requests must already be on replica 1 and complete NOW,
        # while replica 0 is still stuck in its batch
        assert [f.result(timeout=15) for f in queued] == [
            2.0 * 8 * 32, 3.0 * 8 * 32]
        gate.release.set()
        f_wedged.result(timeout=15)
        eng._replicas[0].thread.join(timeout=10.0)
        assert not eng._replicas[0].thread.is_alive()
        # the session re-pinned onto the survivor
        assert eng.policy.sticky_replica("t::s") == 1
        snap = eng.metrics()
        assert snap["completed"] == 3
        assert snap["replicas"]["1"]["requests"] == 2
    finally:
        gate.release.set()
        eng.close()


def test_mesh_wedged_close_fails_queued_and_releases_quota():
    """close() with a wedged worker fails still-queued requests with
    ShutdownError; when the worker unwedges the in-flight member completes
    and every quota slot has been returned exactly once."""
    gate = _MarkGate()
    eng = _mesh_engine(replicas=1, gate=gate)
    f_wedged = eng.submit(_wedge_section(), tenant="t")
    assert gate.started.wait(timeout=10.0)
    f_tail = eng.submit(_section(8, 32, 2.0), tenant="t")
    deadline = time.monotonic() + 5.0
    while eng._replicas[0].queue.qsize() < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    eng.close(timeout=0.2)
    with pytest.raises(ShutdownError):
        f_tail.result(timeout=1.0)
    gate.release.set()
    assert f_wedged.result(timeout=15) == float(
        np.asarray(_wedge_section().data).sum())
    eng._replicas[0].thread.join(timeout=10.0)
    snap = eng.metrics()
    assert snap["completed"] == 1
    assert snap["tenant_table"]["t"]["admitted"] == 0


# --------------------------------------------------------------------------
# ring placement: bit-exactness vs the single-device engine
# --------------------------------------------------------------------------

@pytest.mark.parallel
def test_ring_placement_bit_exact_vs_single_device_engine():
    """A large-geometry request served through the mesh engine's ring
    placement returns the bit-identical peak matrix the single-device
    engine computes — on the kernel path (use_pallas=True, interpret on
    CPU) the sharded program evaluates the same FP ops per pair (the PR 4
    invariant, here re-pinned THROUGH both serving stacks)."""
    from das_diff_veh_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    data = rng.standard_normal((26, 512)).astype(np.float32)
    sec = DasSection(data, np.arange(26, dtype=np.float64),
                     np.arange(512, dtype=np.float64) / 250.0)
    kw = dict(wlen=128, src_chunk=4, use_pallas=True, interpret=True)
    bucket = ((26, 512),)

    single = ServingEngine(
        AllPairsComputeFactory(**kw),
        ServeConfig(buckets=bucket, default_deadline_ms=600000.0)).start()
    mesh_eng = MeshServingEngine(
        AllPairsComputeFactory(mesh=make_mesh(8), **kw),
        MeshServeConfig(
            serve=ServeConfig(buckets=bucket, default_deadline_ms=600000.0),
            replicas=1, ring_min_channels=20)).start()
    try:
        ref = single.submit(sec).result(timeout=120)
        out = mesh_eng.submit(sec).result(timeout=120)
        assert ref.placement == "single" and out.placement == "ring"
        assert out.peaks.shape == (26, 26)
        np.testing.assert_array_equal(out.peaks, ref.peaks)
        snap = mesh_eng.metrics()
        assert snap["placements"] == {"ring:0": 1}
        assert snap["cache_misses"] == 0
        # ring + the one replica were both warmed
        assert snap["warmup_builds"] == 2
        assert snap["mesh"]["ring"] and snap["mesh"]["ring_devices"] == 8
    finally:
        single.close()
        mesh_eng.close()


# --------------------------------------------------------------------------
# HTTP front: tenant field, 429 mapping, merged metrics exposition
# --------------------------------------------------------------------------

def _post(base, path, payload):
    req = urllib.request.Request(base + path, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_tenant_quota_429_and_merged_metrics_views():
    """POST /v1/process carries the tenant; an over-quota submit maps to a
    structured 429; /v1/metrics and /metrics expose the per-tenant and
    per-replica views in the SAME exposition as the base families — no
    second scrape endpoint."""
    gate = _MarkGate()
    eng = _mesh_engine(replicas=1, gate=gate, quota=1)
    server, _ = serve_in_thread(eng)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        f_wedged = eng.submit(_wedge_section(), tenant="cap")
        assert gate.started.wait(timeout=10.0)
        code, body = _post(base, "/v1/process",
                           {"data": _section(8, 32).data.tolist(),
                            "tenant": "cap"})
        assert code == 429
        assert body["cause"] == "quota" and body["tenant"] == "cap"
        gate.release.set()
        f_wedged.result(timeout=15)
        code, body = _post(base, "/v1/process",
                           {"data": _section(8, 32, 2.0).data.tolist(),
                            "tenant": "cap"})
        assert code == 200
        with urllib.request.urlopen(base + "/v1/metrics", timeout=15) as r:
            snap = json.loads(r.read())
        assert snap["tenants"]["cap"]["shed_quota"] == 1
        assert snap["tenants"]["cap"]["completed"] == 2
        assert "replicas" in snap and "placements" in snap
        assert "tenant_table" in snap
        with urllib.request.urlopen(base + "/metrics", timeout=15) as r:
            text = r.read().decode()
        # one exposition: base families AND the mesh families
        assert "das_serve_events_total" in text
        assert 'das_serve_placements_total{placement="replica:0"}' in text
        assert 'das_serve_tenant_events_total{tenant="cap"' in text
        assert 'das_serve_replica_queue_depth{replica="0"}' in text
    finally:
        gate.release.set()
        server.shutdown()
        eng.close()
