import jax.numpy as jnp
import numpy as np
import pytest

from das_diff_veh_tpu.config import WindowConfig
from das_diff_veh_tpu.core.section import VehicleTracks
from das_diff_veh_tpu.models import windows as W
from das_diff_veh_tpu.oracle import windows_ref as OW

RNG = np.random.default_rng(11)


def _linear_traj(x_track, t_track, t_enter, speed):
    """Float arrival sample indices of one vehicle on the tracking grid."""
    dtt = t_track[1] - t_track[0]
    return (t_enter + x_track / speed - t_track[0]) / dtt


@pytest.mark.parametrize("double_sided", [False, True])
def test_traj_mute_mask_matches_reference_loop(double_sided):
    dx = 8.16
    nx, nt = 37, 500
    x_axis = 500.0 + np.arange(nx) * dx
    t_axis = np.arange(nt) * 0.004 + 60.0
    # forward-moving vehicle crossing the window
    traj_t = np.linspace(58.0, 64.0, 40)
    traj_x = 450.0 + (traj_t - traj_t[0]) * 15.0
    ref = OW.ref_traj_mute_mask(x_axis, t_axis, traj_x, traj_t, dx,
                                offset=200.0, alpha=0.3, delta_x=20.0,
                                double_sided=double_sided)
    ours = np.asarray(W.traj_mute_mask(
        jnp.asarray(x_axis), jnp.asarray(t_axis), jnp.asarray(traj_x),
        jnp.asarray(traj_t), jnp.ones(traj_t.size, bool), dx,
        offset=200.0, alpha=0.3, delta_x=20.0, double_sided=double_sided))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-12)


def test_traj_mute_mask_nan_padded_traj():
    """NaN-padded trajectory knots must give the same mask as the compact one."""
    dx = 8.16
    x_axis = np.arange(30) * dx
    t_axis = np.arange(200) * 0.004
    traj_t = np.linspace(-1.0, 2.0, 25)
    traj_x = traj_t * 20.0 + 30.0
    pad = np.full(10, np.nan)
    tt = np.concatenate([traj_t, pad])
    tx = np.concatenate([traj_x, pad])
    valid = np.isfinite(tt)
    a = np.asarray(W.traj_mute_mask(jnp.asarray(x_axis), jnp.asarray(t_axis),
                                    jnp.asarray(traj_x), jnp.asarray(traj_t),
                                    jnp.ones(25, bool), dx))
    b = np.asarray(W.traj_mute_mask(jnp.asarray(x_axis), jnp.asarray(t_axis),
                                    jnp.asarray(tx), jnp.asarray(tt),
                                    jnp.asarray(valid), dx))
    np.testing.assert_allclose(a, b, rtol=1e-9, atol=1e-12)


def test_mute_along_time_is_tukey_row():
    data = jnp.ones((5, 64))
    out = np.asarray(W.mute_along_time(data, alpha=0.3))
    np.testing.assert_allclose(out[2], W.tukey_window(64, 0.3), rtol=1e-12)


def _make_tracks_and_data(n_veh=6, spacing_s=12.0, nt=30000):
    """Vehicles in arrival order; two of them deliberately too close."""
    fs, dt_track = 250.0, 0.02
    x = np.arange(120) * 8.16                       # surface-wave grid
    t = np.arange(nt) / fs
    x_track = np.arange(0.0, x[-1], 1.0)
    t_track = np.arange(0.0, t[-1], dt_track)
    x0 = 500.0
    speeds = RNG.uniform(14, 18, n_veh)
    enters = 5.0 + np.arange(n_veh) * spacing_s + RNG.uniform(0, 2.0, n_veh)
    enters[3] = enters[2] + 2.0                     # violates isolation
    states = np.stack([_linear_traj(x_track, t_track, e, s)
                       for e, s in zip(enters, speeds)])
    # sort rows by arrival at x0 like the detector would
    order = np.argsort(states[:, int(x0)])
    states = states[order]
    data = RNG.standard_normal((x.size, t.size))
    return data, x, t, states, x_track, t_track, x0


def test_select_windows_matches_reference():
    data, x, t, states, x_track, t_track, x0 = _make_tracks_and_data()
    cfg = WindowConfig()
    acc, wins, starts, xsl = OW.ref_select_windows(
        data, x, t, states, x_track, t_track, x0,
        wlen_sw=cfg.wlen_sw, length_sw=cfg.length_sw,
        spatial_ratio=cfg.spatial_ratio)
    tracks = VehicleTracks(t_idx=jnp.asarray(states),
                           valid=jnp.ones(states.shape[0], bool),
                           x=jnp.asarray(x_track), t=jnp.asarray(t_track))
    batch = W.select_windows(jnp.asarray(data), x, t, tracks, x0, cfg)
    got = np.flatnonzero(np.asarray(batch.valid))
    assert list(got) == acc
    assert len(acc) >= 2, "test scene should accept several vehicles"
    for k, ridx in enumerate(acc):
        np.testing.assert_allclose(np.asarray(batch.data[ridx]), wins[k],
                                   rtol=1e-12)
    np.testing.assert_allclose(np.asarray(batch.x), x[xsl], rtol=1e-12)


def test_select_windows_nan_neighbor_skipped():
    """A vehicle with no finite arrival at x0 is not an isolation neighbor:
    the list-adjacent check skips it (matching the oracle), even when the
    finite vehicles on either side are close in time."""
    data, x, t, states, x_track, t_track, x0 = _make_tracks_and_data()
    x0_ti = int(np.abs(x_track - x0).argmin())
    # vehicle 3 tails vehicle 2 closely; marking 3 undetected at the pivot
    # removes it as an isolation neighbor, so vehicle 2 becomes accepted
    states[3, x0_ti] = np.nan
    cfg = WindowConfig()
    acc, _, _, _ = OW.ref_select_windows(
        data, x, t, states, x_track, t_track, x0,
        wlen_sw=cfg.wlen_sw, length_sw=cfg.length_sw,
        spatial_ratio=cfg.spatial_ratio)
    tracks = VehicleTracks(t_idx=jnp.asarray(states),
                           valid=jnp.ones(states.shape[0], bool),
                           x=jnp.asarray(x_track), t=jnp.asarray(t_track))
    batch = W.select_windows(jnp.asarray(data), x, t, tracks, x0, cfg)
    assert list(np.flatnonzero(np.asarray(batch.valid))) == acc
    assert 2 in acc and 3 not in acc


def test_select_windows_rejects_boundary():
    data, x, t, states, x_track, t_track, x0 = _make_tracks_and_data()
    # push first vehicle's arrival to the very start of the record
    states[0] = states[0] - states[0, int(x0)] + 10.0
    tracks = VehicleTracks(t_idx=jnp.asarray(states),
                           valid=jnp.ones(states.shape[0], bool),
                           x=jnp.asarray(x_track), t=jnp.asarray(t_track))
    batch = W.select_windows(jnp.asarray(data), x, t, tracks, x0, WindowConfig())
    assert not bool(batch.valid[0])
