"""Runtime subsystem tests: prefetch, fault isolation, resume manifest, traces.

The integration tests drive the real ``run_directory`` workflow (real npz
I/O through DirectoryDataset, real manifest/state checkpoints, real
prefetch threads) with a cheap deterministic ``compute_fn`` so bit-identity
of the accumulator under faults/resume is asserted without paying the full
imaging pipeline per chunk — ``tests/test_pipeline.py`` covers the
integrated real-compute path (including a quarantined corrupt file).
"""

import json
import os
import threading

import numpy as np
import pytest

from das_diff_veh_tpu.config import ImagingConfig, PipelineConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io.readers import DirectoryDataset, save_section_npz
from das_diff_veh_tpu.pipeline.workflow import run_directory
from das_diff_veh_tpu.runtime import (ChunkTask, PrefetchLoader, RunManifest,
                                      RuntimeConfig, TraceWriter, config_hash,
                                      load_trace, run_pipelined)

DATE = "20230301"


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _section(scale: float) -> DasSection:
    rng = np.random.default_rng(7)
    data = rng.standard_normal((8, 256)) * scale
    return DasSection(data, np.arange(8.0), np.arange(256) / 250.0)


def _write_dir(root, scales, corrupt=()):
    """Write one date folder of tiny npz chunks; ``corrupt`` indices get
    garbage bytes instead of a valid npz."""
    day = os.path.join(str(root), DATE)
    os.makedirs(day, exist_ok=True)
    for i, s in enumerate(scales):
        path = os.path.join(day, f"{DATE}_{i:02d}0000.npz")
        if i in corrupt:
            with open(path, "wb") as f:
                f.write(b"this is not an npz file")
        else:
            save_section_npz(path, _section(s))
    return str(root)


def _fake_compute(section):
    """Deterministic stand-in for process_chunk: (1 vehicle, 4x4 image)."""
    d = np.asarray(section.data)
    return 1, np.outer(d.mean(axis=1)[:4], d.std(axis=1)[:4] + 1.0)


def _dataset(root):
    return DirectoryDataset(DATE, root=root, ch1=None, ch2=None,
                            smoothing=False, rescale_after=None)


def _run(root, out=None, compute=_fake_compute, runtime=None, **kw):
    return run_directory(_dataset(root), out_dir=out, compute_fn=compute,
                         runtime=runtime or RuntimeConfig(), **kw)


# --------------------------------------------------------------------------
# prefetch
# --------------------------------------------------------------------------

@pytest.mark.parametrize("depth", [0, 1, 4])
def test_prefetch_loader_preserves_order(depth):
    loader = PrefetchLoader([lambda i=i: i * i for i in range(12)], depth=depth)
    out = list(loader)
    assert [v for _, v, _ in out] == [i * i for i in range(12)]
    assert all(e is None for _, _, e in out)
    loader.close()


def test_prefetch_loader_runs_in_background_thread():
    names = []

    def load():
        names.append(threading.current_thread().name)
        return 1

    loader = PrefetchLoader([load] * 3, depth=2)
    assert [v for _, v, _ in loader] == [1, 1, 1]
    assert all(n != "MainThread" for n in names)
    loader.close()


def test_prefetch_loader_delivers_errors_in_band():
    def bad():
        raise OSError("boom")

    loader = PrefetchLoader([lambda: 1, bad, lambda: 3], depth=2)
    out = list(loader)
    assert out[0][1] == 1 and out[2][1] == 3
    assert isinstance(out[1][2], OSError)
    loader.close()


# --------------------------------------------------------------------------
# executor: retry / quarantine
# --------------------------------------------------------------------------

def test_executor_retries_transient_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "v"

    acc = []
    stats = run_pipelined([ChunkTask(0, "a", flaky)], compute=lambda v: v + "!",
                          accumulate=lambda t, r: acc.append(r),
                          cfg=RuntimeConfig(max_retries=2, retry_backoff_s=0.0))
    assert acc == ["v!"] and stats.n_done == 1
    assert stats.n_retries == 2 and not stats.quarantined


def test_executor_quarantines_bad_chunk_and_continues():
    def compute(v):
        if v == "bad":
            raise ValueError("shape mismatch")
        return v

    acc = []
    tasks = [ChunkTask(i, k, lambda k=k: k) for i, k in
             enumerate(["a", "bad", "c"])]
    quar = []
    stats = run_pipelined(tasks, compute, lambda t, r: acc.append(r),
                          cfg=RuntimeConfig(max_retries=1, retry_backoff_s=0.0),
                          on_quarantine=quar.append)
    assert acc == ["a", "c"]
    assert [q.key for q in stats.quarantined] == ["bad"]
    assert stats.quarantined[0].stage == "compute"
    assert "ValueError" in stats.quarantined[0].error
    assert quar == stats.quarantined


def test_executor_zero_retries_means_single_attempt():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise OSError("nope")

    stats = run_pipelined([ChunkTask(0, "a", bad)], compute=lambda v: v,
                          accumulate=lambda t, r: None,
                          cfg=RuntimeConfig(prefetch_depth=2, max_retries=0,
                                            retry_backoff_s=0.0))
    assert calls["n"] == 1 and stats.n_retries == 0
    assert [q.stage for q in stats.quarantined] == ["load"]


# --------------------------------------------------------------------------
# tracing
# --------------------------------------------------------------------------

def test_trace_writer_chrome_format(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tw = TraceWriter(path)
    with tw.span("read", file="f0.npz"):
        with tw.span("inner"):
            pass

    def worker():
        with tw.span("preprocess"):
            pass

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    tw.counter("chunks", done=1, quarantined=0)
    tw.instant("retry", stage="load")
    tw.close()

    events = load_trace(path)           # raises on any malformed line
    assert {e["ph"] for e in events} >= {"X", "C", "M", "i"}
    x = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in x} == {"read", "inner", "preprocess"}
    assert all(e["dur"] >= 0 for e in x)
    assert len({e["tid"] for e in x}) == 2          # two threads
    # every line is standalone JSON (crash-safe JSONL)
    with open(path) as f:
        for line in f:
            json.loads(line)


# --------------------------------------------------------------------------
# manifest
# --------------------------------------------------------------------------

def test_manifest_roundtrip(tmp_path):
    path = str(tmp_path / "m.json")
    m = RunManifest(path=path, config_hash=config_hash(PipelineConfig()),
                    date=DATE)
    m.mark_done("a.npz", 3)
    m.mark_done("b.npz", 0)
    m.mark_quarantined("c.npz", "load", "BadZipFile: bad magic", retries=2)
    m.save()
    m2 = RunManifest.load(path)
    assert m2.config_hash == m.config_hash
    assert m2.n_vehicles == 3 and m2.n_chunks == 1
    assert m2.is_settled("a.npz") and m2.is_settled("c.npz")
    assert not m2.is_settled("d.npz")
    assert list(m2.quarantined) == ["c.npz"]


def test_config_hash_sensitivity():
    a = config_hash(PipelineConfig(), "xcorr", True)
    b = config_hash(PipelineConfig().replace(
        imaging=ImagingConfig(x0=500.0)), "xcorr", True)
    c = config_hash(PipelineConfig(), "surface_wave", True)
    assert len({a, b, c}) == 3
    assert a == config_hash(PipelineConfig(), "xcorr", True)


# --------------------------------------------------------------------------
# run_directory integration: fault isolation
# --------------------------------------------------------------------------

def test_fault_injection_bit_identical_average(tmp_path):
    """A corrupt npz mid-directory costs exactly that chunk: the run
    completes, the file is quarantined, and the accumulated average is
    bit-identical to a run over a directory without the file."""
    root_a = _write_dir(tmp_path / "a", [1.0, 1.1, 1.2, 1.3], corrupt=(1,))
    root_b = _write_dir(tmp_path / "b", [1.0, 1.2, 1.3])

    out = str(tmp_path / "res_a")
    res_a = _run(root_a, out=out,
                 runtime=RuntimeConfig(max_retries=1, retry_backoff_s=0.0))
    res_b = _run(root_b)

    assert [q.key for q in res_a.quarantined] == [f"{DATE}_010000.npz"]
    assert res_a.quarantined[0].stage == "load"
    assert res_a.n_chunks == 3 and res_a.complete
    assert np.array_equal(res_a.avg_image, res_b.avg_image)
    assert res_a.n_vehicles == res_b.n_vehicles == 3

    man = RunManifest.load(os.path.join(out, f"{DATE}_manifest.json"))
    assert man.complete and list(man.quarantined) == [f"{DATE}_010000.npz"]

    # a second run over the same out_dir retries nothing — quarantined and
    # done chunks are settled; the accumulator is restored from the state
    calls = {"n": 0}

    def counting(section):
        calls["n"] += 1
        return _fake_compute(section)

    res_c = _run(root_a, out=out, compute=counting)
    assert calls["n"] == 0 and res_c.n_resumed == 4
    assert np.array_equal(res_c.avg_image, res_a.avg_image)


# --------------------------------------------------------------------------
# run_directory integration: kill / restart via the manifest
# --------------------------------------------------------------------------

def test_kill_restart_resume_bit_identical(tmp_path):
    scales = [1.0, 1.5, 2.0, 2.5]
    root = _write_dir(tmp_path / "d", scales)
    out_int = str(tmp_path / "res_int")
    out_ref = str(tmp_path / "res_ref")

    # uninterrupted reference run
    ref = _run(root, out=out_ref)
    assert ref.n_chunks == 4 and ref.complete

    # hard-kill the run mid-date (after 2 chunks committed)
    calls = {"n": 0}

    def killed(section):
        calls["n"] += 1
        if calls["n"] == 3:
            raise KeyboardInterrupt
        return _fake_compute(section)

    with pytest.raises(KeyboardInterrupt):
        _run(root, out=out_int, compute=killed)
    man = RunManifest.load(os.path.join(out_int, f"{DATE}_manifest.json"))
    assert not man.complete and man.n_chunks == 2

    # restart: only the remaining chunks are processed
    calls2 = {"n": 0}

    def counting(section):
        calls2["n"] += 1
        return _fake_compute(section)

    res = _run(root, out=out_int, compute=counting)
    assert calls2["n"] == 2 and res.n_resumed == 2
    assert res.complete and res.n_chunks == 4
    assert np.array_equal(res.avg_image, ref.avg_image)
    assert res.n_vehicles == ref.n_vehicles == 4


def test_max_chunks_truncates_then_resumes(tmp_path):
    root = _write_dir(tmp_path / "d", [1.0, 1.5, 2.0])
    out = str(tmp_path / "res")
    res1 = _run(root, out=out, max_chunks=2)
    assert res1.n_chunks == 2 and not res1.complete
    res2 = _run(root, out=out)
    assert res2.n_resumed == 2 and res2.complete and res2.n_chunks == 3
    full = _run(root)
    assert np.array_equal(res2.avg_image, full.avg_image)


def test_config_change_invalidates_resume(tmp_path):
    root = _write_dir(tmp_path / "d", [1.0, 1.5])
    out = str(tmp_path / "res")
    res1 = _run(root, out=out)
    assert res1.complete and res1.n_chunks == 2

    calls = {"n": 0}

    def counting(section):
        calls["n"] += 1
        return _fake_compute(section)

    # same config: nothing recomputed
    _run(root, out=out, compute=counting)
    assert calls["n"] == 0
    # changed config: stale outputs invalidated, everything recomputed
    res3 = _run(root, out=out, compute=counting,
                cfg=PipelineConfig().replace(imaging=ImagingConfig(x0=500.0)))
    assert calls["n"] == 2 and res3.n_resumed == 0 and res3.complete


def test_stale_manifest_done_entry_is_recomputed(tmp_path):
    """A manifest 'done' entry the state checkpoint never absorbed (crash
    between the two writes) is dropped and recomputed — never double-counted,
    never silently missing from the accumulator."""
    root = _write_dir(tmp_path / "d", [1.0, 1.5])
    out = str(tmp_path / "res")
    res1 = _run(root, out=out, max_chunks=1)
    assert res1.n_chunks == 1
    # forge the crash window: manifest claims chunk 2 done, state lacks it
    mpath = os.path.join(out, f"{DATE}_manifest.json")
    man = RunManifest.load(mpath)
    man.mark_done(f"{DATE}_010000.npz", 1)
    man.save()

    res2 = _run(root, out=out)
    full = _run(root)
    assert res2.n_chunks == 2
    assert np.array_equal(res2.avg_image, full.avg_image)


# --------------------------------------------------------------------------
# run_directory integration: trace output
# --------------------------------------------------------------------------

def test_run_directory_emits_valid_chrome_trace(tmp_path):
    root = _write_dir(tmp_path / "d", [1.0, 1.5])
    trace = str(tmp_path / "trace.jsonl")
    res = _run(root, runtime=RuntimeConfig(prefetch_depth=2, trace_path=trace))
    assert res.n_chunks == 2
    events = load_trace(trace)          # validates every line
    spans = {e["name"] for e in events if e["ph"] == "X"}
    assert {"read", "preprocess", "device_put", "compute",
            "accumulate"} <= spans
    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert {"chunks", "vehicles"} <= counters
    # loader spans and compute spans come from different threads
    tids = {e["tid"] for e in events
            if e["ph"] == "X" and e["name"] in ("read", "compute")}
    assert len(tids) == 2
    assert res.chunks_per_s > 0


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def test_cli_runtime_flags():
    from das_diff_veh_tpu.pipeline.cli import build_parser
    args = build_parser().parse_args(
        ["--data_root", "/d", "--start_date", DATE, "--end_date", DATE,
         "--max_chunks", "5", "--prefetch_depth", "4", "--retries", "2",
         "--retry_backoff", "0.5", "--trace", "/tmp/t.jsonl"])
    assert args.max_chunks == 5 and args.prefetch_depth == 4
    assert args.retries == 2 and args.retry_backoff == 0.5
    assert args.trace == "/tmp/t.jsonl"


def test_cli_missing_args_errors_cleanly(capsys):
    from das_diff_veh_tpu.pipeline.cli import main
    with pytest.raises(SystemExit) as exc:
        main(["--start_date", DATE])
    assert exc.value.code == 2
    assert "required unless --figures" in capsys.readouterr().err
