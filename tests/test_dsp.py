import jax.numpy as jnp
import numpy as np
from scipy import signal as sp_signal

from das_diff_veh_tpu import ops


RNG = np.random.default_rng(42)


def test_tukey_window_matches_scipy():
    for n, alpha in [(100, 0.05), (57, 0.3), (200, 0.6), (10, 1.0), (5, 0.0)]:
        ours = np.asarray(ops.tukey_window(n, alpha))
        theirs = sp_signal.windows.tukey(n, alpha)
        np.testing.assert_allclose(ours, theirs, atol=1e-12, err_msg=f"n={n} alpha={alpha}")


def test_taper_time_matches_reference_semantics():
    data = RNG.standard_normal((6, 300))
    ref = data * sp_signal.windows.tukey(300, 0.05)[None, :]
    ours = np.asarray(ops.taper_time(jnp.asarray(data)))
    np.testing.assert_allclose(ours, ref, atol=1e-12)


def test_detrend_linear_matches_scipy():
    data = RNG.standard_normal((4, 500)) + np.linspace(0, 3, 500)[None, :]
    ref = sp_signal.detrend(data)
    ours = np.asarray(ops.detrend_linear(jnp.asarray(data)))
    np.testing.assert_allclose(ours, ref, atol=1e-9)


def test_common_mode_removal():
    data = RNG.standard_normal((9, 100)) + 5.0
    ours = np.asarray(ops.remove_common_mode(jnp.asarray(data)))
    ref = data - np.median(data, axis=0)
    np.testing.assert_allclose(ours, ref, atol=1e-12)


def test_bandpass_time_matches_sosfiltfilt_interior():
    """FFT zero-phase filtering equals sosfiltfilt in steady state.

    Edge windows differ by design: sosfiltfilt's default padlen (~63 samples)
    is far shorter than the order-10 band filter's transient, so near edges
    *scipy* deviates from the true zero-phase response; our odd-extension
    FFT path uses a transient-length pad.  Documented delta
    (reference modules/utils.py:179-195)."""
    fs, nt, flo, fhi = 250.0, 8000, 1.2, 30.0
    data = RNG.standard_normal((8, nt))
    sos = sp_signal.butter(10, [flo / (fs / 2), fhi / (fs / 2)], btype="band", output="sos")
    ref = sp_signal.sosfiltfilt(sos, data, axis=1)
    ours = np.asarray(ops.bandpass_time(jnp.asarray(data), 1.0 / fs, flo, fhi))
    cut = nt // 4
    scale = np.std(ref[:, cut:-cut])
    err = np.abs(ours[:, cut:-cut] - ref[:, cut:-cut]) / scale
    assert err.max() < 2e-3, err.max()


def test_bandpass_quasistatic_band_amplitude_response():
    """For the 0.08-1 Hz tracking band sosfiltfilt never reaches steady state
    on realistic windows (its padlen ≪ transient), so the oracle is the
    analytic zero-phase response |H(f)|² from sosfreqz."""
    fs, flo, fhi = 250.0, 0.08, 1.0
    nt = 60000
    sos = sp_signal.butter(10, [flo / (fs / 2), fhi / (fs / 2)], btype="band", output="sos")
    for f in [0.03, 0.3, 0.6, 2.0, 5.0]:
        t = np.arange(nt) / fs
        x = np.sin(2 * np.pi * f * t)
        y = np.asarray(ops.bandpass_time(jnp.asarray(x)[None], 1.0 / fs, flo, fhi))[0]
        mid = slice(nt // 3, 2 * nt // 3)
        meas = np.sqrt(np.mean(y[mid] ** 2) / np.mean(x[mid] ** 2))
        _, h = sp_signal.sosfreqz(sos, worN=[f], fs=fs)
        expect = np.abs(h[0]) ** 2
        assert abs(meas - expect) < 0.02 + 0.05 * expect, (f, meas, expect)


def test_bandpass_time_passband_stopband():
    """Frequency-response check: passband preserved, stopband killed."""
    fs = 250.0
    nt = 5000
    t = np.arange(nt) / fs
    inband = np.sin(2 * np.pi * 10.0 * t)
    outband = np.sin(2 * np.pi * 60.0 * t)
    out = np.asarray(ops.bandpass_time(jnp.asarray(inband + outband)[None], 1 / fs, 1.2, 30.0))[0]
    mid = slice(nt // 4, 3 * nt // 4)
    corr_in = np.corrcoef(out[mid], inband[mid])[0, 1]
    assert corr_in > 0.99
    assert np.std(out[mid] - inband[mid]) < 0.05


def test_bandpass_space_noop_sentinel():
    data = jnp.asarray(RNG.standard_normal((16, 50)))
    out = ops.bandpass_space(data, 1.0, -1, -1)
    assert out is data


def test_savgol_matches_scipy():
    data = RNG.standard_normal((5, 242))
    for window, order in [(25, 4), (25, 2), (13, 3), (101, 3)]:
        ref = sp_signal.savgol_filter(data, window, order, axis=-1)
        ours = np.asarray(ops.savgol_filter(jnp.asarray(data), window, order, axis=-1))
        np.testing.assert_allclose(ours, ref, atol=1e-7, err_msg=f"w={window} o={order}")


def test_savgol_high_order_interior():
    """(21,15) — the reference's file pre-smooth (modules/imaging_IO.py:45).
    At order 15 the edge polynomial fit is condition-number ~1e12, so scipy's
    own edge samples are numerically meaningless; compare interiors only."""
    data = RNG.standard_normal((3, 100))
    ref = sp_signal.savgol_filter(data, 21, 15, axis=-1)
    ours = np.asarray(ops.savgol_filter(jnp.asarray(data), 21, 15, axis=-1))
    np.testing.assert_allclose(ours[:, 10:-10], ref[:, 10:-10], atol=1e-7)


def test_savgol_axis0():
    data = RNG.standard_normal((242, 5))
    ref = sp_signal.savgol_filter(data, 25, 4, axis=0)
    ours = np.asarray(ops.savgol_filter(jnp.asarray(data), 25, 4, axis=0))
    np.testing.assert_allclose(ours, ref, atol=1e-8)


def test_resample_poly_matches_scipy():
    data = RNG.standard_normal((37, 200))
    ref = sp_signal.resample_poly(data, 204, 25, axis=0)
    ours = np.asarray(ops.resample_poly(jnp.asarray(data), 204, 25, axis=0))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=1e-8)


def test_resample_poly_identity():
    data = jnp.asarray(RNG.standard_normal((10, 20)))
    out = ops.resample_poly(data, 3, 3, axis=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(data))


def test_welch_matches_scipy():
    fs = 250.0
    data = RNG.standard_normal((3, 2000))
    f_ref, p_ref = sp_signal.welch(data, fs, nperseg=256)
    f_ours, p_ours = ops.welch_psd(jnp.asarray(data), fs, nperseg=256)
    np.testing.assert_allclose(np.asarray(f_ours), f_ref, atol=1e-9)
    np.testing.assert_allclose(np.asarray(p_ours), p_ref, rtol=1e-6, atol=1e-12)


def test_welch_matches_scipy_nfft():
    fs = 250.0
    data = RNG.standard_normal(1500)
    f_ref, p_ref = sp_signal.welch(data, fs, nperseg=256, nfft=1024)
    f_ours, p_ours = ops.welch_psd(jnp.asarray(data), fs, nperseg=256, nfft=1024)
    np.testing.assert_allclose(np.asarray(p_ours), p_ref, rtol=1e-6, atol=1e-12)


def test_qc_masks_and_impute():
    data = RNG.standard_normal((10, 50))
    data[3] = 100.0      # noisy
    data[7] = 0.0        # empty
    noisy = np.asarray(ops.noisy_trace_mask(jnp.asarray(data), 5.0))
    empty = np.asarray(ops.empty_trace_mask(jnp.asarray(data), 0.5))
    assert noisy[3] and not noisy[2]
    assert empty[7] and not empty[6]
    fixed = np.asarray(ops.impute_traces(jnp.asarray(data), jnp.asarray(noisy | empty)))
    np.testing.assert_allclose(fixed[3], data[2] + data[4])
    np.testing.assert_allclose(fixed[7], data[6] + data[8])


def test_impute_first_noisy_matches_reference_rule():
    from das_diff_veh_tpu.ops.qc import impute_first_noisy
    data = RNG.standard_normal((6, 30))
    data[0] = 50.0
    out = np.asarray(impute_first_noisy(jnp.asarray(data), 5.0))
    np.testing.assert_allclose(out[0], data[1])     # edge rule: copy neighbor
    data2 = RNG.standard_normal((6, 30))
    data2[4] = 50.0
    out2 = np.asarray(impute_first_noisy(jnp.asarray(data2), 5.0))
    np.testing.assert_allclose(out2[4], data2[3] + data2[5])


def test_l2_normalize():
    from das_diff_veh_tpu.ops.filters import l2_normalize_traces
    data = RNG.standard_normal((4, 100))
    out = np.asarray(l2_normalize_traces(jnp.asarray(data)))
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, atol=1e-12)
