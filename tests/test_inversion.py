"""Inversion subsystem tests: forward model against analytic oracles,
propagator algebra, differentiability, sensitivity kernels, and end-to-end
profile recovery (SURVEY §7 step 10; reference inversion_diff_*.ipynb)."""

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.inversion import (Curve, LayerBounds, LayeredModel,
                                        ModelSpec, curves_from_ridges,
                                        density_gardner_linear, invert,
                                        make_misfit_fn, phase_sensitivity,
                                        phase_velocity,
                                        rayleigh_halfspace_velocity,
                                        resample_fine, ridge_stats, secular,
                                        speed_model_spec, vp_from_poisson,
                                        weight_model_spec)
from das_diff_veh_tpu.inversion.forward import (_layer_A, _layer_propagator)


def _model(d, vs, nu=0.4375):
    vs = jnp.asarray(vs, dtype=jnp.float64)
    vp = vp_from_poisson(vs, nu)
    return LayeredModel(jnp.asarray(d, dtype=jnp.float64), vp, vs,
                        density_gardner_linear(vp))


class TestLayerSystem:
    def test_coefficient_matrix_eigenvalues(self):
        # A's spectrum must be +-k*nu_p, +-k*nu_s (evanescent regime).
        vp_, vs_, rho_ = 1.5, 0.5, 1.8
        c, k = 0.4, 5.0
        A = np.asarray(_layer_A(jnp.float64(k), jnp.float64(k * c), vp_, vs_,
                                rho_))
        got = np.sort(np.linalg.eigvals(A).real)
        nup = k * np.sqrt(1 - (c / vp_) ** 2)
        nus = k * np.sqrt(1 - (c / vs_) ** 2)
        np.testing.assert_allclose(got, np.sort([-nus, -nup, nup, nus]),
                                   rtol=1e-12)

    def test_propagator_is_expm(self):
        # closed-form polynomial expm == scipy expm (up to the e^-s scale)
        from scipy.linalg import expm
        rng = np.random.default_rng(1)
        for _ in range(20):
            vs_ = rng.uniform(0.2, 1.0)
            vp_ = 3.0 * vs_
            rho_ = rng.uniform(1.6, 2.1)
            c = rng.uniform(0.1, 1.2)
            k = rng.uniform(1.0, 800.0)
            d = rng.uniform(0.001, 0.08)
            A = np.asarray(_layer_A(jnp.float64(k), jnp.float64(k * c), vp_,
                                    vs_, rho_))
            M_ref = expm(A * d)
            M = np.asarray(_layer_propagator(jnp.float64(k),
                                             jnp.float64(k * c), d, vp_, vs_,
                                             rho_))
            M_ref = M_ref / np.abs(M_ref).max()
            M = M / np.abs(M).max()
            i = np.unravel_index(np.abs(M_ref).argmax(), M_ref.shape)
            if np.sign(M_ref[i]) != np.sign(M[i]):
                M = -M
            np.testing.assert_allclose(M, M_ref, atol=1e-10)

    def test_propagator_group_property(self):
        k, om = jnp.float64(5.0), jnp.float64(2.0)
        args = (1.5, 0.5, 1.8)
        M1 = _layer_propagator(k, om, 0.013, *args)
        M2 = _layer_propagator(k, om, 0.027, *args)
        M3 = _layer_propagator(k, om, 0.040, *args)
        # scaled propagators multiply up to a positive factor
        P = np.asarray(M2 @ M1)
        Q = np.asarray(M3)
        np.testing.assert_allclose(P / np.abs(P).max(), Q / np.abs(Q).max(),
                                   atol=1e-12)


class TestPhaseVelocity:
    def test_homogeneous_halfspace_matches_rayleigh_root(self):
        m = _model([0.01, 0.02, 0.0], [0.5, 0.5, 0.5])
        c = phase_velocity(jnp.array([0.05, 0.1, 0.3, 1.0]), m, mode=0)
        cr = rayleigh_halfspace_velocity(float(m.vp[0]), 0.5)
        np.testing.assert_allclose(np.asarray(c), cr, rtol=1e-8)

    def test_two_layer_limits(self):
        m = _model([0.01, 0.0], [0.2, 0.6])
        c = phase_velocity(jnp.array([0.01, 2.0]), m, mode=0)
        c_top = rayleigh_halfspace_velocity(float(m.vp[0]), 0.2)
        c_half = rayleigh_halfspace_velocity(float(m.vp[1]), 0.6)
        assert abs(float(c[0]) - c_top) < 2e-3    # high f -> top layer
        assert abs(float(c[1]) - c_half) < 2e-2   # low f -> halfspace

    def test_normal_dispersion_monotone(self):
        m = _model([0.008, 0.02, 0.0], [0.2, 0.4, 0.7])
        c = np.asarray(phase_velocity(jnp.linspace(0.03, 0.5, 20), m, mode=0))
        assert np.all(np.diff(c) > -1e-9)  # c grows with period

    def test_matches_brute_force_roots_all_modes(self):
        from scipy.optimize import brentq
        model = speed_model_spec().to_model(jnp.full(12, 0.5))
        lo = 0.7 * float(model.vs.min())
        hi = 0.999 * float(model.vs[-1])
        # one compiled scalar secular reused across every brentq call and
        # every (mode, T) case (omega is a traced argument, not a constant)
        sec = jax.jit(secular)
        for mode, T in [(0, 0.2), (0, 0.08), (1, 0.1), (3, 0.069),
                        (4, 0.055)]:
            om = 2 * np.pi / T
            cs = np.linspace(lo, hi, 4000)
            Ds = np.asarray(sec(jnp.asarray(cs), jnp.asarray(om), model))
            flips = np.where(np.sign(Ds[:-1]) * np.sign(Ds[1:]) < 0)[0]
            roots = [brentq(lambda c: float(sec(jnp.asarray(c),
                                                jnp.asarray(om), model)),
                            cs[i], cs[i + 1]) for i in flips]
            mine = float(phase_velocity(jnp.asarray([T]), model, mode=mode,
                                        n_grid=300)[0])
            assert abs(mine - roots[mode]) < 1e-5

    def test_overtone_cutoff_is_nan(self):
        m = _model([0.01, 0.0], [0.2, 0.6])
        c = phase_velocity(jnp.array([1.0]), m, mode=4)
        assert np.isnan(np.asarray(c)).all()

    def test_gradient_matches_finite_differences(self):
        d = jnp.array([0.008, 0.015, 0.0])
        vs = jnp.array([0.25, 0.45, 0.75])
        rho = jnp.full(3, 1.9)

        def cv(vs_):
            mm = LayeredModel(d, 3.0 * vs_, vs_, rho)
            return phase_velocity(jnp.array([0.12]), mm, mode=0)[0]

        g = np.asarray(jax.grad(cv)(vs))
        fd = [(cv(vs + jnp.eye(3)[i] * 1e-6)
               - cv(vs - jnp.eye(3)[i] * 1e-6)) / 2e-6 for i in range(3)]
        np.testing.assert_allclose(g, np.asarray(fd), atol=1e-5)

    def test_float32_agrees_with_float64(self):
        m64 = _model([0.008, 0.02, 0.0], [0.2, 0.4, 0.7])
        m32 = jax.tree.map(lambda a: a.astype(jnp.float32), m64)
        c64 = np.asarray(phase_velocity(jnp.linspace(0.05, 0.4, 8), m64))
        c32 = np.asarray(phase_velocity(
            jnp.linspace(0.05, 0.4, 8, dtype=jnp.float32), m32))
        np.testing.assert_allclose(c32, c64, rtol=2e-4)


class TestSensitivity:
    def test_kernel_depth_ordering_and_positivity(self):
        m = _model([0.01, 0.03, 0.0], [0.25, 0.45, 0.8])
        k_hi = phase_sensitivity(m, period=1 / 15.0, dz=0.005, zmax=0.12)
        k_lo = phase_sensitivity(m, period=1 / 4.0, dz=0.005, zmax=0.12)
        assert np.isfinite(k_hi.kernel).all() and np.isfinite(k_lo.kernel).all()
        assert k_hi.kernel.sum() > 0 and k_lo.kernel.sum() > 0
        # centroid of |kernel| is deeper for the lower frequency
        z = k_hi.depth[:-1]
        cen = lambda k: float((z * np.abs(k.kernel[:-1])).sum()
                              / np.abs(k.kernel[:-1]).sum())
        assert cen(k_lo) > cen(k_hi)

    def test_fine_resampling_preserves_dispersion(self):
        m = _model([0.01, 0.03, 0.0], [0.25, 0.45, 0.8])
        fine = resample_fine(m, dz=0.002, zmax=0.1)
        T = jnp.array([0.08, 0.2])
        c_coarse = np.asarray(phase_velocity(T, m))
        c_fine = np.asarray(phase_velocity(T, fine))
        np.testing.assert_allclose(c_fine, c_coarse, rtol=1e-6)


def _three_layer_problem():
    """Shared synthetic recovery problem: true model, observed curves, and
    the search space."""
    vs_true = [0.20, 0.40, 0.70]
    true = _model([0.006, 0.02, 0.0], vs_true)
    T0 = jnp.linspace(0.05, 0.4, 12)
    c0 = phase_velocity(T0, true, mode=0)
    T1 = jnp.linspace(0.04, 0.1, 6)
    c1 = phase_velocity(T1, true, mode=1)
    curves = [
        Curve(np.asarray(T0), np.asarray(c0), 0, 1.0, 0.01 * np.ones(12)),
        Curve(np.asarray(T1), np.asarray(c1), 1, 1.0, 0.01 * np.ones(6)),
    ]
    spec = ModelSpec(layers=(
        LayerBounds((0.002, 0.012), (0.1, 0.3)),
        LayerBounds((0.01, 0.04), (0.25, 0.55)),
        LayerBounds((0.02, 0.08), (0.5, 1.0)),
    ))
    return vs_true, curves, spec


class TestInvert:
    def test_recovers_synthetic_three_layer_profile(self):
        vs_true, curves, spec = _three_layer_problem()
        res = invert(spec, curves, popsize=24, maxiter=100,
                     n_refine_starts=4, n_refine_steps=50, n_grid=200,
                     seed=0)
        assert float(res.misfit) < 0.5  # well under 1 sigma per point
        np.testing.assert_allclose(np.asarray(res.model.vs), vs_true,
                                   rtol=0.05)

    def test_multirun_batching_mechanics(self):
        # cheap-budget check of the vmapped restart machinery (recovery
        # quality is covered by the single-run test above; the reference-data
        # proof lives in scripts/inversion_parity.py): every run advances,
        # history is the across-run best and decreases, pooled refinement
        # can only improve on the swarm best
        from das_diff_veh_tpu.inversion import invert_multirun

        _, curves, spec = _three_layer_problem()
        res = invert_multirun(spec, curves, n_runs=2, popsize=8, maxiter=24,
                              n_refine_starts=3, n_refine_steps=20,
                              n_grid=150, seed=0)
        assert res.models_x.shape[0] == 2 * 8 + 2 * 4   # pops + refined
        assert np.isfinite(np.asarray(res.misfits)).all()
        hist = np.asarray(res.history)
        assert hist.shape == (24,)
        assert (np.diff(hist) <= 1e-12).all()           # best-so-far trace
        assert float(res.misfit) <= hist[-1] + 1e-6     # refine never hurts

    def test_misfit_penalises_missing_overtone(self):
        # a curve demanding mode 4 at very long period (below cutoff)
        spec = ModelSpec(layers=(LayerBounds((0.002, 0.012), (0.1, 0.3)),
                                 LayerBounds((0.02, 0.08), (0.5, 1.0))))
        curves = [Curve(np.array([2.0]), np.array([0.6]), 4, 1.0,
                        np.array([0.01]))]
        mf = make_misfit_fn(spec, curves, n_grid=200)
        v = float(mf(jnp.full(4, 0.5)))
        assert np.isfinite(v) and v >= 4.9  # INVALID_RESIDUAL floor

    def test_weight_spec_free_poisson_param_count(self):
        assert speed_model_spec().n_params == 12
        assert weight_model_spec().n_params == 18
        m = weight_model_spec().to_model(jnp.full(18, 0.5))
        # nu=0.41 midpoint => vp/vs = sqrt(2*0.59/0.18)
        np.testing.assert_allclose(np.asarray(m.vp / m.vs),
                                   np.sqrt(2 * (1 - 0.41) / (1 - 0.82)),
                                   rtol=1e-12)


class TestCurvePrep:
    def test_ridge_stats_and_band_selection(self):
        freqs = np.linspace(1.0, 10.0, 10)
        boot = np.stack([np.full(4, 300.0), np.full(4, 320.0),
                         np.full(4, 310.0)])
        mean, rng, std = ridge_stats(boot)
        np.testing.assert_allclose(mean, 310.0)
        np.testing.assert_allclose(rng, 20.0)
        curves = curves_from_ridges(freqs, [3.0], [7.0], [boot], [0], [2.0])
        (c,) = curves
        assert c.mode == 0 and c.weight == 2.0
        # band is 3<=f<7 -> freqs 3,4,5,6; periods ascend
        np.testing.assert_allclose(c.period, 1.0 / freqs[2:6][::-1])
        np.testing.assert_allclose(c.velocity, 0.310)
        np.testing.assert_allclose(c.uncertainty, 0.020)

    def test_reference_layout_roundtrip(self, tmp_path):
        p = tmp_path / "x.npz"
        np.savez(p, freqs=np.arange(5.0), freq_lb=np.array([1.0]),
                 freq_ub=np.array([3.0]))
        from das_diff_veh_tpu.inversion import load_reference_ridge_npz
        d = load_reference_ridge_npz(str(p))
        assert set(d) == {"freqs", "freq_lb", "freq_ub"}

    def test_single_bootstrap_repetition(self):
        """One repetition: range and std collapse to zero and the
        uncertainty floor (1e-4 km/s) takes over — a degenerate bootstrap
        must not hand the misfit a divide-by-zero weight."""
        freqs = np.linspace(2.0, 5.0, 4)
        boot = np.asarray([[300.0, 310.0, 320.0, 330.0]])   # (1, nf)
        mean, rng, std = ridge_stats(boot)
        np.testing.assert_allclose(mean, boot[0])
        np.testing.assert_allclose(rng, 0.0)
        np.testing.assert_allclose(std, 0.0)
        (c,) = curves_from_ridges(freqs, [2.0], [6.0], [boot], [0])
        np.testing.assert_allclose(c.uncertainty, 1e-4)
        np.testing.assert_allclose(c.velocity, boot[0][::-1] / 1000.0)

    def test_descending_frequency_reversal(self):
        """Band frequencies ascend -> periods 1/f would descend; the
        reversal pins periods ASCENDING with velocities re-paired to their
        original frequency samples (the evodcinv curve convention the
        fleet packer inherits)."""
        freqs = np.array([2.0, 4.0, 8.0])
        boot = np.array([[200.0, 300.0, 400.0]])   # velocity per freq
        (c,) = curves_from_ridges(freqs, [1.0], [10.0], [boot], [0])
        assert np.all(np.diff(c.period) > 0)
        np.testing.assert_allclose(c.period, [1 / 8.0, 1 / 4.0, 1 / 2.0])
        # the 8 Hz sample (shortest period) keeps its 400 m/s velocity
        np.testing.assert_allclose(c.velocity, [0.4, 0.3, 0.2])

    def test_zero_uncertainty_guard(self):
        """A band where SOME points have zero bootstrap spread floors only
        those points at 1e-4; genuinely spread points keep their range."""
        freqs = np.array([2.0, 4.0])
        boot = np.array([[300.0, 340.0], [300.0, 360.0]])
        (c,) = curves_from_ridges(freqs, [1.0], [5.0], [boot], [0])
        # reversed: index 0 is the 4 Hz point (20 m/s spread), index 1 the
        # 2 Hz point (zero spread -> floored)
        np.testing.assert_allclose(c.uncertainty, [0.020, 1e-4])


def test_multirun_sharded_over_mesh_matches_unsharded():
    """Restart axis sharded over the 8-virtual-device CPU mesh matches the
    unsharded run (restarts are independent).

    The winning restart is pinned tight (``x_best`` at atol=1e-7 — in
    practice bit-identical, and the argmin restart index agrees), but the
    full per-restart ``misfits`` vector gets a measured tolerance: XLA
    fuses the chaotic-PSO update differently under shard_map, and after
    10 iterations of a chaotic map a one-ULP divergence in a *losing*
    restart's trajectory is macroscopic.  Measured on this host: 2/72
    misfit entries violate rtol=1e-6, worst relative difference 1.9e-3
    (abs 4.2e-3) — rtol=5e-3 bounds that with margin while still catching
    any real cross-restart mixup (wrong shard order or a dropped restart
    changes misfits at O(1))."""
    from das_diff_veh_tpu.inversion import invert_multirun
    from das_diff_veh_tpu.parallel import make_mesh

    _, curves, spec = _three_layer_problem()
    kw = dict(n_runs=8, popsize=6, maxiter=10, n_refine_starts=2,
              n_refine_steps=8, n_grid=150, seed=0)
    base = invert_multirun(spec, curves, **kw)
    sharded = invert_multirun(spec, curves, mesh=make_mesh(8), **kw)
    np.testing.assert_allclose(np.asarray(sharded.misfits),
                               np.asarray(base.misfits), rtol=5e-3)
    np.testing.assert_allclose(np.asarray(sharded.x_best),
                               np.asarray(base.x_best), atol=1e-7)
    assert int(np.argmin(np.asarray(sharded.misfits))) == int(
        np.argmin(np.asarray(base.misfits)))


def test_scan_mode_diagnostics_flags_osculating_pair():
    """Round-2 advisory closure: two roots inside one grid cell are
    detected (count-doubling + near-zero |D| dip), and the reference-band
    working resolution n_grid=300 is demonstrably converged.

    The engineered case is a low-velocity-zone model whose modes 2 and 3
    osculate to within 2.8 m/s at 14.4 Hz (probed at n_grid=4000): a
    100-point scan (6.1 m/s spacing) skips the pair — mode counting loses
    exactly two sign changes and ``phase_velocity(mode=3)`` degrades to NaN
    (requested overtone resolved past the halfspace cutoff).
    """
    from das_diff_veh_tpu.inversion import (LayeredModel, phase_velocity,
                                            scan_mode_diagnostics,
                                            vp_from_poisson,
                                            density_gardner_linear)

    vs = jnp.asarray([0.45, 0.20, 0.55, 0.75])
    vp = vp_from_poisson(vs, 0.35)
    lvz = LayeredModel(thickness=jnp.asarray([0.012, 0.010, 0.030, 0.05]),
                       vp=vp, vs=vs, rho=density_gardner_linear(vp))
    per = jnp.asarray([1.0 / 14.4])

    d100 = scan_mode_diagnostics(per, lvz, n_grid=100)
    assert bool(d100["missed"][0]) and bool(d100["dip"][0])
    assert int(d100["count_refined"][0]) - int(d100["count"][0]) == 2
    assert np.isnan(float(phase_velocity(per, lvz, mode=3, n_grid=100)[0]))

    d300 = scan_mode_diagnostics(per, lvz, n_grid=300)
    assert not bool(d300["missed"][0]) and not bool(d300["dip"][0])
    c3 = float(phase_velocity(per, lvz, mode=3, n_grid=300)[0])
    c3_fine = float(phase_velocity(per, lvz, mode=3, n_grid=4000)[0])
    assert abs(c3 - c3_fine) < 2e-4

    # the parity searches' n_grid=300 is converged for a reference-class
    # model across the full scored band (no missed roots, no dips)
    vs2 = jnp.asarray([0.2564, 0.3239, 0.4466, 0.3589, 0.5101, 0.8131])
    vp2 = vp_from_poisson(vs2, 0.4375)
    clean = LayeredModel(
        thickness=jnp.asarray([6.0, 7.3, 5.8, 10.6, 31.3, 50.0]) / 1000.0,
        vp=vp2, vs=vs2, rho=density_gardner_linear(vp2))
    d = scan_mode_diagnostics(jnp.asarray(1.0 / np.arange(1.0, 25.0, 0.25)),
                              clean, n_grid=300)
    assert not bool(d["missed"].any()) and not bool(d["dip"].any())
