"""Observability layer tests: registry, exposition, sink, flight, hooks.

Everything is stub-driven — no ``process_chunk`` traces (tier-1 budget).
The one jit in this module is a scalar lambda (millisecond compile) used to
prove the ``jax.monitoring`` counters see real lowerings.  The end-to-end
path (batch run -> trace + metrics JSONL + forced flight dump ->
``scripts/obs_report.py``) reuses test_runtime's cheap ``compute_fn``
pattern.
"""

import json
import os
import re
import signal
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

from das_diff_veh_tpu.config import ObsConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io.readers import DirectoryDataset, save_section_npz
from das_diff_veh_tpu.obs import (FlightRecorder, HBMSampler, MetricsRegistry,
                                  MetricsSink, ProfilerWindow, load_flight_dump,
                                  load_metrics_jsonl, register_memory_gauges,
                                  xla_events)
from das_diff_veh_tpu.pipeline.workflow import run_directory
from das_diff_veh_tpu.runtime import (ChunkTask, RuntimeConfig, TraceWriter,
                                      load_trace, run_pipelined)

DATE = "20230301"


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("das_t_total", "things", labels=("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    c.labels(kind="b").inc(5)
    assert c.labels(kind="a").value == 3
    assert c.labels(kind="b").value == 5
    with pytest.raises(ValueError, match=">= 0"):
        c.labels(kind="a").inc(-1)

    g = reg.gauge("das_depth")
    g.set(4)
    assert g.value == 4
    g.set_fn(lambda: 9)
    assert g.value == 9
    g.set_fn(lambda: 1 / 0)            # a dead provider must not kill reads
    assert g.value == 9                # last good value

    h = reg.histogram("das_lat_ms", window=4)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):
        h.observe(v)
    assert h.count == 5 and h.sum == 15.0      # monotonic despite the ring
    assert h.values() == [2.0, 3.0, 4.0, 5.0]  # bounded window
    p = h.percentiles()
    assert p["p50"] == 4.0 and p["n"] == 4 and p["max"] == 5.0


def test_registry_reregistration_and_mismatch():
    reg = MetricsRegistry()
    a = reg.counter("das_x_total", labels=("k",))
    assert reg.counter("das_x_total", labels=("k",)) is a   # idempotent
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("das_x_total", labels=("k",))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("das_x_total", labels=("other",))
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad name!")
    with pytest.raises(ValueError, match="invalid label"):
        reg.counter("das_ok_total", labels=("bad-label",))
    with pytest.raises(ValueError, match="expected labels"):
        a.labels(wrong="x")


# one exposition-format checker shared with the serve HTTP test
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"(?:[^\"\\\n]|\\.)*\"(?:,[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"(?:[^\"\\\n]|\\.)*\")*\})? -?[0-9.e+-]+(?:[0-9]|inf|nan)?$")


def assert_prometheus_wellformed(text: str) -> dict:
    """Validate exposition lines; returns {metric_name: type}."""
    types = {}
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, ptype = line.split(" ", 3)
            assert ptype in ("counter", "gauge", "summary"), line
            types[name] = ptype
        else:
            assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
            base = line.split("{")[0].split(" ")[0]
            stripped = re.sub(r"_(sum|count)$", "", base)
            assert base in types or stripped in types, \
                f"sample without TYPE: {line!r}"
    return types


def test_prometheus_exposition_wellformed_and_escaped():
    reg = MetricsRegistry()
    reg.counter("das_e_total", "events", labels=("name",)).labels(
        name='we"ird\\path\nx').inc()
    reg.gauge("das_g", "a gauge").set(-2.5)
    h = reg.histogram("das_h_ms", "ring")
    h.observe(1.5)
    types = assert_prometheus_wellformed(reg.prometheus_text())
    assert types == {"das_e_total": "counter", "das_g": "gauge",
                     "das_h_ms": "summary"}
    text = reg.prometheus_text()
    assert 'name="we\\"ird\\\\path\\nx"' in text
    assert 'das_h_ms{quantile="0.99"} 1.5' in text


def test_registry_to_json_shape():
    reg = MetricsRegistry()
    reg.counter("das_a_total").inc(3)
    reg.histogram("das_b_ms").observe(2.0)
    j = reg.to_json()
    assert j["das_a_total"] == {"kind": "counter", "values": {"()": 3.0}}
    hb = j["das_b_ms"]["values"]["()"]
    assert hb["count"] == 1 and hb["p50"] == 2.0


# --------------------------------------------------------------------------
# JSONL sink
# --------------------------------------------------------------------------

def test_metrics_sink_writes_parseable_lines(tmp_path):
    reg = MetricsRegistry()
    c = reg.counter("das_n_total")
    path = str(tmp_path / "metrics.jsonl")
    sink = MetricsSink(reg, path, interval_s=60.0)   # ticks won't fire; we do
    c.inc()
    sink.flush()
    c.inc()
    sink.close()                                      # final snapshot line
    snaps = load_metrics_jsonl(path)
    assert len(snaps) == 2
    assert snaps[0]["metrics"]["das_n_total"]["values"]["()"] == 1.0
    assert snaps[-1]["metrics"]["das_n_total"]["values"]["()"] == 2.0
    assert snaps[0]["ts"] <= snaps[-1]["ts"]
    sink.close()                                      # idempotent

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"no": "keys"}\n')
    with pytest.raises(ValueError, match="missing ts/metrics"):
        load_metrics_jsonl(str(bad))


def test_metrics_sink_appends_across_runs_and_creates_parent(tmp_path):
    # run_date_range builds one sink per date against the same path: the
    # second open must append, not truncate the first date's snapshots
    path = str(tmp_path / "deep" / "dir" / "metrics.jsonl")   # parent made
    for run in range(2):
        reg = MetricsRegistry()
        reg.counter("das_run_total").inc(run + 1)
        sink = MetricsSink(reg, path, interval_s=60.0)
        sink.close()
    snaps = load_metrics_jsonl(path)
    assert len(snaps) == 2
    assert snaps[0]["metrics"]["das_run_total"]["values"]["()"] == 1.0
    assert snaps[1]["metrics"]["das_run_total"]["values"]["()"] == 2.0


# --------------------------------------------------------------------------
# trace writer flush batching (satellite: no syscall per span by choice)
# --------------------------------------------------------------------------

def test_trace_writer_default_flushes_per_event(tmp_path):
    path = str(tmp_path / "t.jsonl")
    w = TraceWriter(path)
    with w.span("s1"):
        pass
    # durability: the span is on disk BEFORE close (crash-safe default)
    assert any(json.loads(ln)["name"] == "s1"
               for ln in open(path) if ln.strip())
    w.close()


def test_trace_writer_batched_flush_defers_then_close_flushes(tmp_path):
    path = str(tmp_path / "t.jsonl")
    w = TraceWriter(path, flush_interval_s=3600.0)
    for i in range(50):
        with w.span(f"s{i}"):
            pass
    # nothing (beyond at most the first buffer fill) should have hit disk
    assert os.path.getsize(path) == 0
    w.flush()
    assert os.path.getsize(path) > 0
    with w.span("tail"):
        pass
    w.close()                      # close always flushes the tail
    events = load_trace(path)      # every line valid Chrome-trace
    names = {e["name"] for e in events}
    assert "s0" in names and "s49" in names and "tail" in names


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

def test_flight_ring_bound_and_dump_schema(tmp_path):
    fr = FlightRecorder(capacity=4, out_dir=str(tmp_path), name="f")
    for i in range(10):
        fr.record("chunk", key=f"k{i}")
    path = fr.dump("quarantine", key="k9")
    payload = load_flight_dump(path)
    assert payload["reason"] == "quarantine"
    assert payload["context"] == {"key": "k9"}
    assert payload["n_recorded"] == 10
    keys = [r["key"] for r in payload["records"]]
    assert keys == ["k6", "k7", "k8", "k9"]        # last capacity records
    # rate limit: a second dump for the same reason inside the window is
    # suppressed; force overrides; another reason is its own window
    assert fr.dump("quarantine") is None
    assert fr.dump("quarantine", force=True) is not None
    assert fr.dump("shed") is not None
    assert fr.n_dumps == 3


def test_flight_dump_names_unique_across_recorder_instances(tmp_path):
    # bench A/B reps (and a re-run date) build fresh recorders with the
    # same name in one process; dump filenames must never collide
    paths = []
    for rep in range(2):
        fr = FlightRecorder(capacity=2, out_dir=str(tmp_path), name="same")
        fr.record("chunk", rep=rep)
        paths.append(fr.dump("quarantine", force=True))
    assert paths[0] != paths[1]
    assert load_flight_dump(paths[0])["records"][0]["rep"] == 0
    assert load_flight_dump(paths[1])["records"][0]["rep"] == 1


def test_flight_without_out_dir_records_but_never_writes(tmp_path):
    fr = FlightRecorder(capacity=2)
    fr.record("request", shape=[4, 16])
    assert fr.dump("error") is None
    assert len(fr.records()) == 1
    # explicit path still dumps (obs_report tooling, tests)
    p = str(tmp_path / "explicit.json")
    assert fr.dump("error", path=p) == p
    assert load_flight_dump(p)["records"][0]["shape"] == [4, 16]


def test_flight_signal_handler_dumps_and_chains(tmp_path):
    fr = FlightRecorder(capacity=8, out_dir=str(tmp_path), name="sig")
    fr.record("chunk", key="k0")
    seen = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: seen.append(s))
    try:
        assert fr.install_signal_handlers(signals=(signal.SIGUSR1,))
        signal.raise_signal(signal.SIGUSR1)
        dumps = [f for f in os.listdir(tmp_path) if f.startswith("sig_sig")]
        assert len(dumps) == 1                     # dumped on the signal
        assert seen == [signal.SIGUSR1]            # chained to previous
        fr.uninstall_signal_handlers()
        signal.raise_signal(signal.SIGUSR1)
        assert seen == [signal.SIGUSR1] * 2        # fully restored
    finally:
        signal.signal(signal.SIGUSR1, prev)


# --------------------------------------------------------------------------
# jax.monitoring hooks
# --------------------------------------------------------------------------

def test_xla_event_counters_see_fresh_compiles_and_stay_flat_cached():
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    watch = xla_events.install(reg)
    try:
        assert watch.traces == 0                   # families exist at zero
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        f(jnp.float32(3.0)).block_until_ready()
        after_compile = watch.traces
        assert after_compile >= 1                  # fresh lowering counted
        for _ in range(3):
            f(jnp.float32(4.0)).block_until_ready()
        assert watch.traces == after_compile       # cache hits: no events
    finally:
        xla_events.uninstall(reg)
    f2 = jax.jit(lambda x: x * 5.0 - 2.0)
    f2(jnp.float32(1.0)).block_until_ready()
    assert watch.traces == after_compile           # unsubscribed: flat


def test_xla_event_subscriptions_are_refcounted():
    """Two components sharing one registry (the serve CLI's engine + an
    in-process batch run both install the process default): the first
    component's uninstall must not freeze the other's counters."""
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    watch = xla_events.install(reg)            # component A (serve engine)
    xla_events.install(reg)                    # component B (a batch run)
    xla_events.uninstall(reg)                  # B finishes first
    try:
        jax.jit(lambda x: x * 3.0 + 9.0)(jnp.float32(1.0)).block_until_ready()
        assert watch.traces >= 1               # A still counting
    finally:
        xla_events.uninstall(reg)              # A releases the last ref
    n = watch.traces
    jax.jit(lambda x: x / 3.0 - 4.0)(jnp.float32(1.0)).block_until_ready()
    assert watch.traces == n                   # fully unsubscribed now


def test_xla_event_install_is_idempotent():
    reg = MetricsRegistry()
    xla_events.install(reg)
    xla_events.install(reg)
    try:
        import jax
        import jax.numpy as jnp
        jax.jit(lambda x: x - 7.0)(jnp.float32(2.0)).block_until_ready()
        fam = reg.get("das_jax_traces_total")
        n = fam.value
        assert n >= 1
        # double-install must not double-count
        assert n == xla_events.CompileWatch(reg).traces
    finally:
        xla_events.uninstall(reg)
        xla_events.uninstall(reg)                  # idempotent


# --------------------------------------------------------------------------
# profiling hooks
# --------------------------------------------------------------------------

def test_profiler_window_captures_steady_state_steps(tmp_path):
    import jax.numpy as jnp

    reg = MetricsRegistry()
    win = ProfilerWindow(str(tmp_path / "prof"), start_after=2, n_steps=1,
                         registry=reg)
    for _ in range(4):
        (jnp.ones(8) * 2).block_until_ready()
        win.step()
    win.close()
    assert win.captured
    assert reg.gauge("das_obs_profiled_steps").value == 1
    # the capture landed on disk (plugins/... structure is backend-specific)
    captured = [os.path.join(dp, f)
                for dp, _, fs in os.walk(tmp_path / "prof") for f in fs]
    assert captured, "profiler window produced no artifact"


def test_memory_gauges_and_sampler_degrade_gracefully_on_cpu():
    reg = MetricsRegistry()
    register_memory_gauges(reg)                    # CPU: memory_stats None
    assert reg.get("das_device_bytes_in_use") is not None
    assert reg.get("das_device_peak_bytes") is not None
    reg.prometheus_text()                          # scrape never raises
    s = HBMSampler(reg, interval_s=0.01)
    time.sleep(0.05)
    s.close()


# --------------------------------------------------------------------------
# executor + workflow wiring (stub compute — no process_chunk)
# --------------------------------------------------------------------------

def test_run_pipelined_registers_metrics_and_dumps_on_quarantine(tmp_path):
    reg = MetricsRegistry()
    fr = FlightRecorder(capacity=16, out_dir=str(tmp_path), name="rt")
    tasks = [ChunkTask(i, f"t{i}", (lambda i=i: i)) for i in range(5)]

    def compute(v):
        if v == 3:
            raise ValueError("poisoned chunk")
        return v

    got = []
    stats = run_pipelined(tasks, compute, lambda t, r: got.append(r),
                          cfg=RuntimeConfig(max_retries=1,
                                            retry_backoff_s=0.0),
                          registry=reg, flight=fr)
    assert stats.n_done == 4 and len(stats.quarantined) == 1
    chunks = reg.counter("das_runtime_chunks_total", labels=("status",))
    assert chunks.labels(status="done").value == 4
    assert chunks.labels(status="quarantined").value == 1
    retries = reg.counter("das_runtime_retries_total", labels=("stage",))
    assert retries.labels(stage="compute").value == 1
    assert reg.histogram("das_runtime_chunk_seconds").count == 4
    assert reg.get("das_runtime_prefetch_depth") is not None
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("rt_quarantine")]
    assert len(dumps) == 1
    payload = load_flight_dump(os.path.join(tmp_path, dumps[0]))
    assert payload["context"] == {"key": "t3", "stage": "compute"}
    failed = [r for r in payload["records"] if r.get("error")]
    assert failed and failed[0]["key"] == "t3"
    assert "poisoned chunk" in failed[0]["error"]


def test_obs_disabled_is_genuinely_off(tmp_path):
    """``ObsConfig.enabled=False`` (the bench A/B's bare side): no registry
    counting, no flight artifacts — even with a flight_dir configured."""
    from das_diff_veh_tpu.obs import default_registry

    reg = default_registry()
    fam = reg.get("das_runtime_chunks_total")
    before = fam.labels(status="done").value if fam is not None else 0.0
    tasks = [ChunkTask(i, f"t{i}", (lambda i=i: i)) for i in range(3)]
    off = ObsConfig(enabled=False, flight_dir=str(tmp_path))
    stats = run_pipelined(tasks, lambda v: v, lambda t, r: None,
                          cfg=RuntimeConfig(max_retries=0, obs=off))
    assert stats.n_done == 3
    fam = reg.get("das_runtime_chunks_total")
    after = fam.labels(status="done").value if fam is not None else 0.0
    assert after == before                     # nothing counted anywhere
    assert os.listdir(tmp_path) == []          # and nothing written


def _write_dir(root, n_files, corrupt=()):
    day = os.path.join(str(root), DATE)
    os.makedirs(day, exist_ok=True)
    rng = np.random.default_rng(3)
    for i in range(n_files):
        path = os.path.join(day, f"{DATE}_{i:02d}0000.npz")
        if i in corrupt:
            with open(path, "wb") as f:
                f.write(b"not an npz")
        else:
            sec = DasSection(rng.standard_normal((6, 128)),
                             np.arange(6.0), np.arange(128) / 250.0)
            save_section_npz(path, sec)
    return str(root)


def _fake_compute(section):
    d = np.asarray(section.data)
    return 1, np.outer(d.mean(axis=1)[:3], d.std(axis=1)[:3] + 1.0)


def test_run_directory_obs_disabled(tmp_path):
    """The workflow's disabled path: all obs handles None, result intact."""
    root = _write_dir(tmp_path / "data", 2)
    res = run_directory(
        DirectoryDataset(DATE, root=root, ch1=None, ch2=None,
                         smoothing=False, rescale_after=None),
        compute_fn=_fake_compute,
        runtime=RuntimeConfig(max_retries=0,
                              obs=ObsConfig(enabled=False)))
    assert res.n_chunks == 2 and not res.quarantined


def test_run_directory_emits_all_obs_artifacts_and_report_renders(tmp_path):
    """The end-to-end observability path the verify recipe exercises: one
    batch run (stub compute, one corrupt file) leaves a trace, a metrics
    JSONL, and a quarantine flight dump, and ``scripts/obs_report.py``
    joins all three into a report."""
    root = _write_dir(tmp_path / "data", 4, corrupt=(2,))
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    trace = str(obs_dir / "trace.jsonl")
    metrics = str(obs_dir / "metrics.jsonl")
    runtime = RuntimeConfig(
        prefetch_depth=2, max_retries=0, trace_path=trace,
        obs=ObsConfig(metrics_jsonl=metrics, metrics_interval_s=30.0,
                      flight_dir=str(obs_dir), trace_flush_interval_s=0.05,
                      hbm_sample_interval_s=0.02))   # sampler wired + closed
    res = run_directory(
        DirectoryDataset(DATE, root=root, ch1=None, ch2=None,
                         smoothing=False, rescale_after=None),
        compute_fn=_fake_compute, runtime=runtime)
    assert res.n_chunks == 3 and len(res.quarantined) == 1

    load_trace(trace)                              # valid despite batching
    snaps = load_metrics_jsonl(metrics)            # final line always written
    assert snaps
    last = snaps[-1]["metrics"]
    done = last["das_runtime_chunks_total"]["values"]['{status="done"}']
    assert done >= 3                               # global registry: >=
    dumps = [str(obs_dir / f) for f in os.listdir(obs_dir)
             if f.startswith(f"flight_{DATE}_quarantine")]
    assert len(dumps) == 1
    payload = load_flight_dump(dumps[0])
    kinds = {r["kind"] for r in payload["records"]}
    assert "run" in kinds and "chunk" in kinds     # config hash + chunks

    import obs_report
    out = str(obs_dir / "report.txt")
    rc = obs_report.main(["--flight", dumps[0], "--trace", trace,
                          "--metrics", metrics, "--out", out])
    assert rc == 0
    report = open(out).read()
    assert "## flight dump" in report and "## trace" in report \
        and "## metrics" in report
    assert "quarantine" in report
    assert "das_runtime_chunks_total" in report
    assert re.search(r"failed-record join .*\.npz", report)


def test_obs_report_rejects_malformed_artifacts(tmp_path, capsys):
    import obs_report
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert obs_report.main(["--flight", str(bad)]) == 2
    assert "failed to parse" in capsys.readouterr().err
