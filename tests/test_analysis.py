import jax.numpy as jnp
import numpy as np
import pytest
from scipy.interpolate import interp1d

from das_diff_veh_tpu.analysis import (bootstrap_disp, classify_by_speed,
                                       classify_by_weight, convergence_test,
                                       extract_ridge, majority_speed_mask,
                                       majority_weight_mask,
                                       quasi_static_peaks, sample_indices,
                                       vehicle_speeds)
from das_diff_veh_tpu.config import BootstrapConfig, DispersionConfig
from das_diff_veh_tpu.core.section import VehicleTracks, WindowBatch
from das_diff_veh_tpu.models.vsg import gather_disp_image
from das_diff_veh_tpu.oracle.ridge_ref import ref_extract_ridge

RNG = np.random.default_rng(17)


def _fv_map(nvel=400, nfreq=120):
    """Smooth dispersion-like map: one bright dispersive ridge + texture."""
    vels = np.arange(200.0, 200.0 + nvel)
    freqs = np.linspace(2.0, 20.0, nfreq)
    ridge = 500.0 - 8.0 * (freqs - 2.0)
    fv = np.exp(-0.5 * ((vels[:, None] - ridge[None, :]) / 40.0) ** 2)
    fv += 0.1 * RNG.random((nvel, nfreq))
    return freqs, vels, fv


@pytest.mark.parametrize("mode", ["none", "ref_idx", "ref_vel"])
def test_extract_ridge_matches_reference(mode):
    freqs, vels, fv = _fv_map()
    kw = {}
    if mode == "none":
        kw = dict(vel_max=520.0)
    elif mode == "ref_idx":
        kw = dict(ref_freq_idx=60, sigma=30.0)
    else:
        kw = dict(ref_vel=lambda f: 500.0 - 8.0 * (f - 2.0), sigma=30.0)
    ref = ref_extract_ridge(freqs, vels, fv, **kw)
    ours = np.asarray(extract_ridge(freqs, vels, jnp.asarray(fv), **kw))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


def test_vehicle_speeds_from_linear_tracks():
    x = np.arange(300.0)
    t_track = np.arange(4000) * 0.02
    speeds_true = np.array([12.0, 18.0])
    t_idx = np.stack([(5.0 + x / s) / 0.02 for s in speeds_true])
    tracks = VehicleTracks(t_idx=jnp.asarray(t_idx), valid=jnp.ones(2, bool),
                           x=jnp.asarray(x), t=jnp.asarray(t_track))
    got = np.asarray(vehicle_speeds(tracks))
    np.testing.assert_allclose(got, speeds_true, rtol=1e-6)


def test_quasi_static_peaks_scale_with_weight():
    nt, nx = 2000, 30
    t = np.arange(nt) * 0.004
    pulse = np.exp(-0.5 * ((t - 4.0) / 0.8) ** 2)
    def batch_for(amp):
        data = np.tile(-amp * pulse, (nx, 1))[None]
        return WindowBatch(data=jnp.asarray(data), x=jnp.zeros(nx),
                           t=jnp.asarray(t[None]), traj_x=jnp.zeros((1, 4)),
                           traj_t=jnp.zeros((1, 4)),
                           valid=jnp.ones(1, bool))
    p1 = float(quasi_static_peaks(batch_for(1.0))[0])
    p2 = float(quasi_static_peaks(batch_for(2.5))[0])
    assert p2 > 2.0 * p1 > 0


def test_classification_masks():
    speeds = np.concatenate([RNG.normal(15, 1, 200), [30.0, 31.0], [5.0]])
    fast, mid, slow = classify_by_speed(speeds)
    assert fast.sum() >= 2 and slow.sum() >= 1
    assert not (fast & mid).any() and not (mid & slow).any()
    assert majority_speed_mask(speeds).sum() > 150

    peaks = np.concatenate([RNG.normal(0.8, 0.05, 300), RNG.uniform(1.3, 3.0, 20)])
    heavy, midw, light = classify_by_weight(peaks)
    assert heavy.sum() == 20
    assert (heavy | midw | light).sum() == peaks.size
    assert majority_weight_mask(peaks).sum() > 100


def test_sample_indices_excludes_first():
    idx = sample_indices(50, 10, 20, np.random.default_rng(0))
    assert idx.shape == (20, 10)
    assert idx.min() >= 1
    for row in idx:
        assert len(set(row.tolist())) == 10


def test_bootstrap_disp_matches_direct_stack():
    """A single repetition must equal stacking those windows directly."""
    nwin, nch, wlen = 8, 20, 250
    gathers = jnp.asarray(RNG.standard_normal((nwin, nch, wlen)))
    offsets = (np.arange(nch) - nch + 1) * 8.16
    dcfg = DispersionConfig(freq_step=0.5, vel_step=10.0)
    cfg = BootstrapConfig(bt_times=1, bt_size=3, sigma=(30.0,),
                          ref_freq_idx=(10,), freq_lb=(3.0,), freq_ub=(16.0,))
    idx = np.array([[1, 4, 6]])
    ridges, freqs = bootstrap_disp(gathers, offsets, 0.004, 8.16, idx,
                                   cfg, dcfg)
    stack = jnp.mean(gathers[jnp.asarray(idx[0])], axis=0)
    img = gather_disp_image(stack, offsets, 0.004, 8.16, dcfg, -150.0, 0.0)
    band = (freqs >= 3.0) & (freqs < 16.0)
    vels = np.arange(dcfg.vel_min, dcfg.vel_max, dcfg.vel_step)
    ref_idx = int(10 - np.sum(freqs < 3.0))
    expect = np.asarray(extract_ridge(freqs[band], vels,
                                      img[:, jnp.asarray(band)],
                                      ref_freq_idx=ref_idx, sigma=30.0,
                                      vel_max=cfg.vel_max))
    np.testing.assert_allclose(ridges[0][0], expect, rtol=1e-9, atol=1e-9)


def test_convergence_test_shape():
    nwin, nch, wlen = 10, 16, 200
    gathers = jnp.asarray(RNG.standard_normal((nwin, nch, wlen)))
    offsets = (np.arange(nch) - nch + 1) * 8.16
    dcfg = DispersionConfig(freq_step=0.25, vel_step=25.0)
    cfg = BootstrapConfig(bt_times=3, sigma=(50.0,), ref_freq_idx=(12,),
                          freq_lb=(3.0,), freq_ub=(12.0,))
    out = convergence_test(gathers, offsets, 0.004, 8.16, 4, 3,
                           np.random.default_rng(1), cfg, dcfg)
    assert out.shape == (1, 4)
    assert np.isfinite(out).all()


def test_extract_ridge_batch_matches_single():
    """The batched jitted ridge program equals per-image extract_ridge in
    all three modes (plain argmax / reference-index walk / reference
    curve)."""
    from das_diff_veh_tpu.analysis import extract_ridge_batch

    freqs, vels, _ = _fv_map()
    maps = jnp.asarray(np.stack([_fv_map()[2] for _ in range(4)]))
    for kw in (dict(vel_max=450.0),
               dict(ref_freq_idx=30, sigma=40.0),
               dict(ref_vel=interp1d(freqs, 500.0 - 8.0 * (freqs - 2.0)),
                    sigma=40.0)):
        got = np.asarray(extract_ridge_batch(freqs, vels, maps, **kw))
        want = np.stack([np.asarray(extract_ridge(freqs, vels, maps[i], **kw))
                         for i in range(maps.shape[0])])
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)


def test_bootstrap_counts_padding_equivalence():
    """Padded index rows + counts reproduce the unpadded bootstrap exactly:
    the padding slots are masked out of the stack mean."""
    nwin, nch, wlen = 8, 20, 250
    gathers = jnp.asarray(RNG.standard_normal((nwin, nch, wlen)))
    offsets = (np.arange(nch) - nch + 1) * 8.16
    dcfg = DispersionConfig(freq_step=0.5, vel_step=10.0)
    cfg = BootstrapConfig(bt_times=3, bt_size=3, sigma=(30.0,),
                          ref_freq_idx=(10,), freq_lb=(3.0,), freq_ub=(16.0,))
    idx = sample_indices(nwin, 3, 3, np.random.default_rng(5))
    plain, _ = bootstrap_disp(gathers, offsets, 0.004, 8.16, idx, cfg, dcfg)
    padded = np.concatenate(
        [idx, np.broadcast_to(idx[:, :1], (3, 4))], axis=1)
    masked, _ = bootstrap_disp(gathers, offsets, 0.004, 8.16, padded, cfg,
                               dcfg, counts=np.full(3, 3))
    np.testing.assert_allclose(masked[0], plain[0], rtol=1e-10)


def test_convergence_study_compiles_once():
    """VERDICT r3 item 7: the bt_size sweep must NOT retrace per size —
    padded index rows keep every jitted stage's shapes constant, so each
    stage gains at most one cache entry for the whole study."""
    from das_diff_veh_tpu.analysis.bootstrap import (_image_batch,
                                                     _resample_stacks_counts)
    from das_diff_veh_tpu.analysis.ridge import _ridge_batch

    nwin, nch, wlen = 10, 20, 250
    # local rng: the physics assertion below depends on the realization, so
    # it must not float with the module-global stream's consumption order
    gathers = jnp.asarray(
        np.random.default_rng(21).standard_normal((nwin, nch, wlen)))
    offsets = (np.arange(nch) - nch + 1) * 8.16
    dcfg = DispersionConfig(freq_step=0.5, vel_step=10.0)
    cfg = BootstrapConfig(bt_times=3, bt_size=3, sigma=(30.0,),
                          ref_freq_idx=(10,), freq_lb=(3.0,), freq_ub=(16.0,))
    before = (_resample_stacks_counts._cache_size(),
              _image_batch._cache_size(), _ridge_batch._cache_size())
    out = convergence_test(gathers, offsets, 0.004, 8.16, max_sample_num=5,
                           bt_times=3, rng=np.random.default_rng(0), cfg=cfg,
                           disp_cfg=dcfg)
    after = (_resample_stacks_counts._cache_size(),
             _image_batch._cache_size(), _ridge_batch._cache_size())
    assert out.shape == (1, 5) and np.isfinite(out).all()
    # no spread-vs-size physics assertion here: on pure-noise gathers the
    # gated ridge walk's std is not monotone in bt_size — the study's
    # physics is exercised on structured scenes elsewhere; THIS test pins
    # the compile-once property
    grow = np.array(after) - np.array(before)
    assert (grow <= 1).all(), f"stage retraced during bt_size sweep: {grow}"
