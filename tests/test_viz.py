"""Visualization layer: figures render, files get written, and the
norm_part re-normalization matches the reference algebra
(modules/utils.py:528-543)."""

import matplotlib
import numpy as np

matplotlib.use("Agg")

from das_diff_veh_tpu import viz  # noqa: E402

RNG = np.random.default_rng(3)


def test_norm_part_matches_reference_algebra():
    nf, nv = 40, 30
    freqs = np.linspace(2.0, 25.0, nf)
    vels = np.linspace(200.0, 1200.0, nv)
    fv = np.abs(RNG.standard_normal((nv, nf))) + 0.1

    got = viz.apply_norm_part(fv, freqs, vels)

    # reference algebra (utils.py:528-543), written independently: global
    # per-frequency max norm, then the (f>10, v>600) window re-normalized
    # by its own per-frequency max
    ref = fv / fv.max(axis=0)
    hf = freqs > 10.0
    hv = vels > 600.0
    win = fv[np.ix_(np.where(hv)[0], np.where(hf)[0])]
    ref[np.ix_(np.where(hv)[0], np.where(hf)[0])] = win / win.max(axis=0)
    np.testing.assert_allclose(got, ref, rtol=1e-12)


def test_plot_fv_map_and_gather_write_files(tmp_path):
    fv = np.abs(RNG.standard_normal((50, 60)))
    freqs = np.linspace(1.0, 25.0, 60)
    vels = np.linspace(200.0, 1200.0, 50)
    p1 = tmp_path / "fv.png"
    viz.plot_fv_map(fv, freqs, vels, fig_path=str(p1))
    assert p1.exists() and p1.stat().st_size > 0

    xcf = RNG.standard_normal((28, 100))
    lags = (np.arange(100) - 50) * 0.004
    offs = np.linspace(-150.0, 70.0, 28)
    p2 = tmp_path / "gather.png"
    viz.plot_gather(xcf, lags, offs, fig_path=str(p2))
    assert p2.exists() and p2.stat().st_size > 0


def test_plot_disp_curves_returns_reference_stats(tmp_path):
    freqs = np.linspace(1.0, 20.0, 50)
    band = RNG.normal(400.0, 5.0, size=(8, np.sum((freqs >= 3) & (freqs < 9))))
    means, ranges, stds = viz.plot_disp_curves(
        freqs, [3.0], [9.0], [band], fig_path=str(tmp_path / "dc.png"))
    np.testing.assert_allclose(means[0], band.mean(0))
    np.testing.assert_allclose(ranges[0], band.max(0) - band.min(0))
    np.testing.assert_allclose(stds[0], band.std(0))


def test_model_ensemble_plot(tmp_path):
    from das_diff_veh_tpu.inversion import speed_model_spec

    spec = speed_model_spec()
    X = RNG.uniform(0.2, 0.8, size=(20, 12))
    mis = RNG.uniform(0.1, 2.0, size=20)
    p = tmp_path / "ens.png"
    viz.plot_model_ensemble(X, mis, spec, fig_path=str(p))
    assert p.exists() and p.stat().st_size > 0


def test_figure_set_from_synthetic(tmp_path):
    files = viz.figure_set_from_synthetic(str(tmp_path), n_windows=3)
    assert len(files) >= 5
    for f in files:
        assert (tmp_path / f.split("/")[-1]).exists()


def test_plot_detection_writes_file(tmp_path):
    # a few pulse trains -> traces with clear peaks and a stacked likelihood
    rng = np.random.default_rng(7)
    fs, dur = 50.0, 40.0
    t = np.arange(int(dur * fs)) / fs
    nch = 15
    data = rng.standard_normal((nch + 4, t.size)) * 0.01
    for arr in (8.0, 22.0):
        for c in range(4, 4 + nch):
            data[c] += np.exp(-0.5 * ((t - arr) / 0.15) ** 2)
    p = str(tmp_path / "det.png")
    viz.plot_detection(data, t, start_x_idx=4, fig_path=p)
    import os
    assert os.path.getsize(p) > 0


def test_gather_spectra_plots_write_files(tmp_path):
    rng = np.random.default_rng(9)
    xcf = rng.standard_normal((30, 500))
    offs = np.linspace(-150.0, 0.0, 30)
    p1 = str(tmp_path / "psd_off.png")
    p2 = str(tmp_path / "spec_off.png")
    viz.plot_psd_vs_offset(xcf, offs, dt=1 / 250.0, log_scale=True,
                           fig_path=p1)
    viz.plot_spectrum_vs_offset(xcf, offs, dt=1 / 250.0, fig_path=p2)
    import os
    assert os.path.getsize(p1) > 0 and os.path.getsize(p2) > 0


def test_plot_convergence_writes_file(tmp_path):
    spreads = np.abs(np.random.default_rng(11).standard_normal((3, 20)))
    p = str(tmp_path / "conv.png")
    viz.plot_convergence(spreads, fig_path=p)
    import os
    assert os.path.getsize(p) > 0


def test_plot_fk_writes_file(tmp_path):
    from das_diff_veh_tpu.ops.dispersion import fk_transform
    import jax.numpy as jnp
    rng = np.random.default_rng(12)
    data = rng.standard_normal((30, 400))
    mag, f, k = fk_transform(jnp.asarray(data), dx=8.16, dt=1 / 250.0)
    p = str(tmp_path / "fk.png")
    viz.plot_fk(np.asarray(mag), np.asarray(f), np.asarray(k), fig_path=p)
    import os
    assert os.path.getsize(p) > 0


def test_plot_predicted_curves_overlay(tmp_path):
    import jax.numpy as jnp
    from das_diff_veh_tpu.inversion import (Curve, LayeredModel,
                                            density_gardner_linear,
                                            phase_velocity, vp_from_poisson)
    vs = jnp.asarray([0.2, 0.5])
    vp = vp_from_poisson(vs, 0.4375)
    m = LayeredModel(jnp.asarray([0.01, 0.0]), vp, vs,
                     density_gardner_linear(vp))
    T = np.linspace(0.05, 0.3, 10)
    obs = np.asarray(phase_velocity(jnp.asarray(T), m, mode=0))
    curves = [Curve(T, obs, 0, 1.0, 0.01 * np.ones_like(T))]
    p = str(tmp_path / "pred.png")
    viz.plot_predicted_curves(m, curves, fig_path=p)
    import os
    assert os.path.getsize(p) > 0
