"""Tuner subsystem tests: store robustness, sweep mechanics, integration.

The store contract under test is the warmup-safety one (ISSUE 19 satellite):
persistence round-trips, a config-hash mismatch is a plain miss (re-tune,
never stale winners), and a corrupt/truncated/foreign-version store file
degrades to default knobs with a warning — no failure mode may crash a
batch start or a serve warmup.  Sweeps run against stub timers (no real
kernel timing in tier-1); the serve integration runs on the stub-factory
pattern so zero fresh ``process_chunk`` programs are traced.
"""

import json
import logging
import os
import warnings

import numpy as np
import pytest

from das_diff_veh_tpu.config import (PipelineConfig, RingConfig, ServeConfig)
from das_diff_veh_tpu.runtime import RuntimeConfig, consult_tuner
from das_diff_veh_tpu.tune import (STORE_VERSION, KnobSpec, TunedEntry,
                                   TunerStore, apply_winners, base_hash,
                                   load_tuned, store_key, sweep_knobs, tune)


# --------------------------------------------------------------------------
# store: persistence + every failure mode degrades to defaults
# --------------------------------------------------------------------------

def test_store_round_trip(tmp_path):
    p = str(tmp_path / "tuner.json")
    s = TunerStore(p)
    entry = TunedEntry({"ring.win_block": 16},
                       {"baseline_s": 1.0, "tuned_s": 0.5})
    s.record("cpu", "fiberA", "abcd1234", entry)
    got = TunerStore(p).lookup("cpu", "fiberA", "abcd1234")
    assert got is not None
    assert got.winners == {"ring.win_block": 16}
    assert got.meta["tuned_s"] == 0.5


def test_store_miss_on_hash_backend_or_geometry_mismatch(tmp_path):
    p = str(tmp_path / "tuner.json")
    s = TunerStore(p)
    s.record("cpu", "fiberA", "abcd1234", TunedEntry({"ring.win_block": 16}))
    fresh = TunerStore(p)
    assert fresh.lookup("cpu", "fiberA", "deadbeef") is None   # config changed
    assert fresh.lookup("tpu", "fiberA", "abcd1234") is None   # other backend
    assert fresh.lookup("cpu", "fiberB", "abcd1234") is None   # other geometry


@pytest.mark.parametrize("content", [
    "{not json",                                     # corrupt
    "",                                              # truncated to nothing
    json.dumps({"version": STORE_VERSION + 1,
                "entries": {"cpu|g|h": {"winners": {}}}}),  # foreign version
    json.dumps([1, 2, 3]),                           # wrong top-level type
    json.dumps({"version": STORE_VERSION,
                "entries": {"cpu|g|h": "not-a-dict"}}),     # malformed entry
])
def test_store_bad_file_warns_and_falls_back(tmp_path, caplog, content):
    p = str(tmp_path / "tuner.json")
    with open(p, "w") as f:
        f.write(content)
    with caplog.at_level(logging.WARNING, logger="das_diff_veh_tpu.tune"):
        assert TunerStore(p).lookup("cpu", "g", "h") is None
    assert any("falling back" in r.message for r in caplog.records)


def test_store_missing_file_is_empty_no_warning(tmp_path, caplog):
    with caplog.at_level(logging.WARNING, logger="das_diff_veh_tpu.tune"):
        assert TunerStore(str(tmp_path / "absent.json")).lookup(
            "cpu", "g", "h") is None
    assert not caplog.records


def test_load_tuned_never_raises_on_bad_store(tmp_path):
    """The warmup entry point: any store problem returns defaults."""
    p = str(tmp_path / "tuner.json")
    with open(p, "w") as f:
        f.write("\x00garbage")
    cfg = PipelineConfig()
    out, ring, entry = load_tuned(cfg, p, "g", backend="cpu")
    assert out == cfg and entry is None


# --------------------------------------------------------------------------
# apply_winners: whitelist enforcement
# --------------------------------------------------------------------------

def test_apply_winners_dotted_paths_and_ring_root():
    cfg, ring = apply_winners(
        PipelineConfig(),
        {"gather.fused_max_nwin": 128, "gather.dot_max_wlen": 512,
         "ring.win_block": 16, "chunk_pipeline": "fused"},
        RingConfig())
    assert cfg.gather.fused_max_nwin == 128
    assert cfg.gather.dot_max_wlen == 512
    assert cfg.chunk_pipeline == "fused"
    assert ring.win_block == 16


def test_apply_winners_skips_non_whitelisted(caplog):
    """Physics and precision knobs are never obeyed from a store."""
    base = PipelineConfig()
    with caplog.at_level(logging.WARNING, logger="das_diff_veh_tpu.tune"):
        cfg, _ = apply_winners(base, {"gather.precision": "bf16",
                                      "gather.wlen": 99.0,
                                      "no.such.path": 1})
    assert cfg == base
    assert sum("not in the tunable whitelist" in r.message
               for r in caplog.records) == 3


def test_apply_winners_ring_knob_without_ring_is_skipped(caplog):
    with caplog.at_level(logging.WARNING, logger="das_diff_veh_tpu.tune"):
        cfg, ring = apply_winners(PipelineConfig(), {"ring.win_block": 16})
    assert ring is None and cfg == PipelineConfig()
    assert any("needs a RingConfig" in r.message for r in caplog.records)


def test_knobspec_rejects_non_whitelisted_path():
    with pytest.raises(ValueError, match="not a tunable knob"):
        KnobSpec("gather.precision", ("bf16",))


# --------------------------------------------------------------------------
# base_hash: stable across apply, sensitive to physics
# --------------------------------------------------------------------------

def test_base_hash_stable_under_winner_application():
    cfg = PipelineConfig()
    tuned, _ = apply_winners(cfg, {"gather.fused_max_nwin": 128,
                                   "chunk_pipeline": "fused"})
    assert base_hash(tuned) == base_hash(cfg)


def test_base_hash_changes_with_physics():
    cfg = PipelineConfig()
    other = cfg.replace(gather=cfg.gather.__class__(wlen=3.0))
    assert base_hash(other) != base_hash(cfg)


# --------------------------------------------------------------------------
# sweep: greedy descent against stub timers
# --------------------------------------------------------------------------

def test_sweep_picks_fastest_candidate():
    times = {None: 1.0, 8: 0.8, 16: 0.4, 32: 0.6}

    def t(cfg, ring):
        return times[ring.win_block]

    entry = sweep_knobs(PipelineConfig(),
                        [KnobSpec("ring.win_block", (8, 16, 32))],
                        t, reps=2, ring=RingConfig())
    assert entry.winners == {"ring.win_block": 16}
    assert entry.meta["baseline_s"] == 1.0
    assert entry.meta["tuned_s"] == 0.4
    assert entry.meta["speedup"] == pytest.approx(2.5)


def test_sweep_keeps_default_when_it_wins():
    def t(cfg, ring):           # every candidate slower than the default
        return 0.5 if ring.win_block is None else 1.0

    entry = sweep_knobs(PipelineConfig(),
                        [KnobSpec("ring.win_block", (8, 16))],
                        t, reps=1, ring=RingConfig())
    assert entry.winners == {}
    assert entry.meta["speedup"] == pytest.approx(1.0)


def test_sweep_is_greedy_across_knobs():
    """Knob 2 is swept with knob 1's winner already applied."""
    def t(cfg, ring):
        base = 1.0 if ring.win_block != 16 else 0.5
        # lag_tile_max=256 only helps once win_block=16 won
        if ring.win_block == 16 and ring.lag_tile_max == 256:
            base -= 0.2
        return base

    entry = sweep_knobs(PipelineConfig(),
                        [KnobSpec("ring.win_block", (8, 16)),
                         KnobSpec("ring.lag_tile_max", (256,))],
                        t, reps=1, ring=RingConfig())
    assert entry.winners == {"ring.win_block": 16, "ring.lag_tile_max": 256}


def test_tune_hits_store_without_resweeping(tmp_path):
    calls = []

    def t(cfg, ring):
        calls.append(1)
        return 1.0 if ring.win_block is None else 0.5

    store = TunerStore(str(tmp_path / "t.json"))
    knobs = [KnobSpec("ring.win_block", (16,))]
    _, ring1, e1 = tune(store, "cpu", "g", PipelineConfig(), knobs, t,
                        reps=1, ring=RingConfig())
    assert ring1.win_block == 16 and calls
    n_sweep = len(calls)
    _, ring2, e2 = tune(store, "cpu", "g", PipelineConfig(), knobs, t,
                        reps=1, ring=RingConfig())
    assert len(calls) == n_sweep        # no re-measurement on the hit
    assert ring2.win_block == 16 and e2.winners == e1.winners
    # a physics change is a miss -> re-sweep
    other = PipelineConfig().replace(
        gather=PipelineConfig().gather.__class__(wlen=3.0))
    tune(store, "cpu", "g", other, knobs, t, reps=1, ring=RingConfig())
    assert len(calls) > n_sweep


# --------------------------------------------------------------------------
# runtime integration: consult_tuner
# --------------------------------------------------------------------------

def test_consult_tuner_disabled_is_identity():
    cfg = PipelineConfig()
    out, entry = consult_tuner(cfg, RuntimeConfig())
    assert out == cfg and entry is None


def test_consult_tuner_applies_winners_and_changes_manifest_hash(tmp_path):
    from das_diff_veh_tpu.runtime import config_hash
    p = str(tmp_path / "t.json")
    cfg = PipelineConfig()
    TunerStore(p).record("cpu", "fiberA", base_hash(cfg),
                         TunedEntry({"gather.fused_max_nwin": 128}))
    rt = RuntimeConfig(tuner_store=p, tuner_geometry="fiberA")
    out, entry = consult_tuner(cfg, rt)
    assert entry is not None
    assert out.gather.fused_max_nwin == 128
    # the tuned knob participates in the resume-manifest hash: a tuned run
    # and a default run never share manifest/state
    assert config_hash(out) != config_hash(cfg)


def test_consult_tuner_corrupt_store_is_identity(tmp_path):
    p = str(tmp_path / "t.json")
    with open(p, "w") as f:
        f.write("{broken")
    cfg = PipelineConfig()
    out, entry = consult_tuner(cfg, RuntimeConfig(tuner_store=p))
    assert out == cfg and entry is None


# --------------------------------------------------------------------------
# serve integration: tuned warmup keeps the zero-compile SLO
# --------------------------------------------------------------------------

def test_imaging_factory_applies_store_before_config_key(tmp_path):
    from das_diff_veh_tpu.serve import ImagingComputeFactory
    p = str(tmp_path / "t.json")
    cfg = PipelineConfig()
    TunerStore(p).record("cpu", "fiberA", base_hash(cfg),
                         TunedEntry({"gather.dot_max_wlen": 512}))
    default_f = ImagingComputeFactory(cfg)
    tuned_f = ImagingComputeFactory(cfg, tuner_store=p,
                                    tuner_geometry="fiberA")
    assert tuned_f.cfg.gather.dot_max_wlen == 512
    assert tuned_f.tuner_entry is not None
    # tuned and default deployments must never share cache entries
    assert tuned_f.config_key != default_f.config_key


def test_imaging_factory_corrupt_store_never_crashes(tmp_path):
    from das_diff_veh_tpu.serve import ImagingComputeFactory
    p = str(tmp_path / "t.json")
    with open(p, "w") as f:
        f.write("\x00")
    f = ImagingComputeFactory(PipelineConfig(), tuner_store=p)
    assert f.tuner_entry is None
    assert f.config_key == ImagingComputeFactory(PipelineConfig()).config_key


def test_tuned_engine_warmup_zero_steady_state_compiles(tmp_path):
    """cache_misses == 0 still holds with tuned values active: the factory
    applies winners before config_key, so the warmed program IS the tuned
    program (stub compute — no fresh process_chunk traces in tier-1)."""
    from das_diff_veh_tpu.core.section import DasSection
    from das_diff_veh_tpu.serve import FnComputeFactory, ServingEngine

    p = str(tmp_path / "t.json")
    cfg = PipelineConfig()
    TunerStore(p).record("cpu", "fiberA", base_hash(cfg),
                         TunedEntry({"gather.fused_max_nwin": 128}))
    tuned_cfg, _, entry = load_tuned(cfg, p, "fiberA", backend="cpu")
    assert entry is not None

    def build(bucket):
        def fn(section, valid, state):
            d = np.asarray(section.data)[:valid[0], :valid[1]]
            return float(d.sum()), state
        return fn

    factory = FnComputeFactory(build, f"tuned:{base_hash(tuned_cfg)}")
    factory.tuner_entry = entry           # serve-side tuned provenance
    eng = ServingEngine(factory, ServeConfig(buckets=((8, 32),))).start()
    try:
        sec = DasSection(np.ones((8, 32), np.float32),
                         np.arange(8, dtype=np.float64) * 8.16,
                         np.arange(32, dtype=np.float64) / 250.0)
        for _ in range(3):
            assert eng.process(sec, timeout=30) == 8 * 32
        m = eng.metrics()
        assert m["warmup_builds"] == 1
        assert m["tuned_warmups"] == 1       # compile_cache logged the consult
        assert m["cache_misses"] == 0        # the SLO holds with tuned knobs
    finally:
        eng.close()


# --------------------------------------------------------------------------
# satellite: batch_window_ms deprecation
# --------------------------------------------------------------------------

def test_batch_window_ms_non_default_warns():
    with pytest.warns(DeprecationWarning, match="batch_window_ms"):
        ServeConfig(batch_window_ms=5.0)


def test_batch_window_ms_default_is_silent():
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ServeConfig()
        ServeConfig(batch_window_ms=2.0)
