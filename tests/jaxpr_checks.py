"""Structural jaxpr assertions shared by the kernel and parallel tests.

These pin *program structure*, not numbers: the streaming/sharding claims
of ``ops.pallas_xcorr`` and ``parallel.allpairs`` (no window-axis padding,
no receiver-set broadcast) are asserted on the traced jaxpr so a regression
fails in tier-1 on CPU, not only as a memory blow-up on the chip.
"""

import jax


def iter_eqns(jaxpr, skip_primitives=()):
    """Yield every equation of ``jaxpr``, recursing through the sub-jaxprs
    carried in equation params (scan/pjit/cond/shard_map/...).  Equations
    whose primitive is in ``skip_primitives`` are skipped entirely
    (neither yielded nor recursed into)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in skip_primitives:
            continue
        yield eqn
        for p in eqn.params.values():
            for j in (p if isinstance(p, (list, tuple)) else [p]):
                if isinstance(j, jax.core.ClosedJaxpr):
                    yield from iter_eqns(j.jaxpr, skip_primitives)
                elif isinstance(j, jax.core.Jaxpr):
                    yield from iter_eqns(j, skip_primitives)


def iter_eqns_outside_kernels(jaxpr):
    """:func:`iter_eqns` minus ``pallas_call`` bodies: slicing *inside* a
    kernel runs once per grid step on a VMEM-resident tile (the fused
    gather's in-kernel shift), which is exactly what replaces an XLA-level
    serialized slice chain — only equations in the surrounding program
    count against the no-chain claims."""
    return iter_eqns(jaxpr, skip_primitives=("pallas_call",))


def record_cut_slices(closed_jaxpr, record_len):
    """Equations *outside any Pallas kernel* that cut the time axis of a
    record-shaped operand: ``gather``/``dynamic_slice`` whose operand's
    last dim is at least ``record_len`` and whose output's last dim is
    smaller.  A vmapped traced-start ``dynamic_slice`` over channels — the
    serialized O(nch) slice chain the fused gather kernel exists to
    replace — appears here as exactly such a gather; the fused path must
    produce NONE (its data-dependent cut lives inside ``pallas_call``)."""
    found = []
    for eqn in iter_eqns_outside_kernels(closed_jaxpr.jaxpr):
        if eqn.primitive.name not in ("gather", "dynamic_slice"):
            continue
        src = getattr(eqn.invars[0].aval, "shape", ())
        dst = getattr(eqn.outvars[0].aval, "shape", ())
        if (src and dst and src[-1] >= record_len and dst[-1] < src[-1]):
            found.append(eqn)
    return found


def has_primitive(closed_jaxpr, name):
    """True iff an equation with the named primitive appears anywhere."""
    return any(e.primitive.name == name for e in iter_eqns(closed_jaxpr.jaxpr))


def window_axis_pads(closed_jaxpr, nwin):
    """Every pad equation that grows axis 1 of a rank-3 spectra-shaped
    operand with ``nwin`` windows — i.e. a zero-padded window-axis copy of
    a spectra array (the thing the win_block streaming exists to avoid)."""
    found = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "pad":
            src, dst = eqn.invars[0].aval, eqn.outvars[0].aval
            if (len(src.shape) == 3 and src.shape[1] == nwin
                    and dst.shape[1] != nwin):
                found.append(eqn)
    return found


def collective_eqns(closed_jaxpr, names=("all_gather", "all_to_all")):
    """Equations whose primitive is one of the named collectives, anywhere
    in the program (shard_map bodies included)."""
    return [e for e in iter_eqns(closed_jaxpr.jaxpr)
            if e.primitive.name in names]


# --------------------------------------------------------------------------
# host-sync detection (PR 16): the fused per-chunk program claims "zero
# intermediate host syncs".  Two structural checks pin it:
#
# 1. :func:`trace_or_host_sync` — JAX turns EVERY implicit device->host
#    coercion of a traced value (``np.asarray``/``__array__``, ``float()``,
#    ``int()``/``__index__``, ``bool()``) into a trace-time error, so "the
#    region traces to a jaxpr at all" is itself the proof that no implicit
#    pull survives inside it.  The staged path validates the detector: its
#    ``int(n_windows)`` epilogue must raise :class:`HostSync`.
# 2. :func:`host_sync_eqns` — the only way a *traced* program can still
#    round-trip to the host at run time is a callback primitive (or
#    infeed/outfeed); the fused program's jaxpr must contain none.
# --------------------------------------------------------------------------

HOST_CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback",
                            "debug_print", "callback", "infeed", "outfeed")


class HostSync(Exception):
    """The traced region synchronizes a traced value back to the host."""


def trace_or_host_sync(fn, *args):
    """Trace ``fn(*args)`` to a ClosedJaxpr, or raise :class:`HostSync` if
    tracing hits an implicit device->host coercion of a traced value.
    ``args`` may be ``jax.ShapeDtypeStruct``s — the detector never needs
    real buffers."""
    import jax.errors as jex
    sync_errors = tuple(
        getattr(jex, n) for n in
        ("TracerArrayConversionError", "ConcretizationTypeError",
         "TracerIntegerConversionError", "TracerBoolConversionError")
        if hasattr(jex, n))
    try:
        return jax.make_jaxpr(fn)(*args)
    except sync_errors as e:  # noqa: B030 — tuple built above
        raise HostSync(str(e)) from e


def host_sync_eqns(closed_jaxpr, names=HOST_CALLBACK_PRIMITIVES):
    """Equations anywhere in the program that can round-trip to the host at
    run time (callback/infeed/outfeed primitives).  Empty for the fused
    chunk program — one dispatch in, one pytree out, nothing in between."""
    return [e for e in iter_eqns(closed_jaxpr.jaxpr)
            if e.primitive.name in names]


def shard_body_full_set_avals(closed_jaxpr, n_full, nwin):
    """Equations *inside a shard_map body* that bind a rank-3 value shaped
    like the FULL receiver spectra set — (n_full, nwin, ...) — i.e. a
    per-device materialization of all ``n_full`` channels' windowed
    spectra.  The ring decomposition's O(nch/D) memory claim holds iff this
    is empty; the replicated layout trips it by construction (which is how
    the checker itself is validated)."""
    found = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        body = eqn.params.get("jaxpr")
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        for var in list(body.invars) + [
                v for e in iter_eqns(body) for v in e.outvars]:
            shape = getattr(var.aval, "shape", ())
            if len(shape) == 3 and shape[0] == n_full and shape[1] == nwin:
                found.append(var)
    return found
