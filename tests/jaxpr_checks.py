"""Structural jaxpr assertions shared by the kernel and parallel tests.

These pin *program structure*, not numbers: the streaming/sharding claims
of ``ops.pallas_xcorr`` and ``parallel.allpairs`` (no window-axis padding,
no receiver-set broadcast) are asserted on the traced jaxpr so a regression
fails in tier-1 on CPU, not only as a memory blow-up on the chip.
"""

import jax


def iter_eqns(jaxpr):
    """Yield every equation of ``jaxpr``, recursing through the sub-jaxprs
    carried in equation params (scan/pjit/cond/shard_map/...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            for j in (p if isinstance(p, (list, tuple)) else [p]):
                if isinstance(j, jax.core.ClosedJaxpr):
                    yield from iter_eqns(j.jaxpr)
                elif isinstance(j, jax.core.Jaxpr):
                    yield from iter_eqns(j)


def window_axis_pads(closed_jaxpr, nwin):
    """Every pad equation that grows axis 1 of a rank-3 spectra-shaped
    operand with ``nwin`` windows — i.e. a zero-padded window-axis copy of
    a spectra array (the thing the win_block streaming exists to avoid)."""
    found = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "pad":
            src, dst = eqn.invars[0].aval, eqn.outvars[0].aval
            if (len(src.shape) == 3 and src.shape[1] == nwin
                    and dst.shape[1] != nwin):
                found.append(eqn)
    return found


def collective_eqns(closed_jaxpr, names=("all_gather", "all_to_all")):
    """Equations whose primitive is one of the named collectives, anywhere
    in the program (shard_map bodies included)."""
    return [e for e in iter_eqns(closed_jaxpr.jaxpr)
            if e.primitive.name in names]


def shard_body_full_set_avals(closed_jaxpr, n_full, nwin):
    """Equations *inside a shard_map body* that bind a rank-3 value shaped
    like the FULL receiver spectra set — (n_full, nwin, ...) — i.e. a
    per-device materialization of all ``n_full`` channels' windowed
    spectra.  The ring decomposition's O(nch/D) memory claim holds iff this
    is empty; the replicated layout trips it by construction (which is how
    the checker itself is validated)."""
    found = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        body = eqn.params.get("jaxpr")
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        for var in list(body.invars) + [
                v for e in iter_eqns(body) for v in e.outvars]:
            shape = getattr(var.aval, "shape", ())
            if len(shape) == 3 and shape[0] == n_full and shape[1] == nwin:
                found.append(var)
    return found
