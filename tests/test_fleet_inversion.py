"""Fleet inversion engine: packed misfit parity, one-program contract,
credible intervals, and Vs change detection (inversion/fleet.py).

Tier-1 budget note (ROADMAP): the module-scoped ``small_fleet`` fixture is
the ONLY fresh fleet compile tier-1 pays here — every non-slow test reuses
its result and its warm jit caches.  The multi-shape trace-count protocol,
the mesh run, and the per-target ``invert_multirun`` equivalence each need
additional compile sets and ride the ``slow`` marker.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from das_diff_veh_tpu.inversion import (Curve, LayerBounds, LayeredModel,
                                        ModelSpec, density_gardner_linear,
                                        invert_fleet, invert_multirun,
                                        make_misfit_fn, make_packed_misfit_fn,
                                        pack_curve_sets, phase_velocity,
                                        speed_model_spec, vp_from_poisson,
                                        weight_model_spec)
from das_diff_veh_tpu.inversion.fleet import detect_vs_shifts

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tiny CPU-smoke search budget shared by every fleet run in this module —
# ONE budget => one compiled program set per (T, tc) shape
BUDGET = dict(n_runs=2, popsize=5, maxiter=6, n_refine_starts=2,
              n_refine_steps=5, n_grid=120)


def _three_layer_spec():
    return ModelSpec(layers=(LayerBounds((0.002, 0.012), (0.1, 0.3)),
                             LayerBounds((0.01, 0.04), (0.25, 0.55)),
                             LayerBounds((0.02, 0.08), (0.5, 1.0))))


def _truth_model():
    vs = jnp.asarray([0.20, 0.40, 0.70], dtype=jnp.float64)
    vp = vp_from_poisson(vs, 0.4375)
    return LayeredModel(thickness=jnp.asarray([0.006, 0.02, 0.0]), vp=vp,
                        vs=vs, rho=density_gardner_linear(vp))


def _curve_sets(n_targets, n_pts=12, seed=1, ragged=False):
    """n_targets noisy bootstrap replicates of the truth's mode-0 curve.

    ``ragged=True`` drops trailing points from every second target and adds
    a short mode-1 overtone curve to the first, so packing actually pads.
    """
    periods = np.linspace(0.05, 0.4, n_pts)
    c0 = np.asarray(phase_velocity(jnp.asarray(periods), _truth_model(),
                                   mode=0, n_grid=400), dtype=np.float64)
    rng = np.random.default_rng(seed)
    sets = []
    for t in range(n_targets):
        n = n_pts - 3 if (ragged and t % 2) else n_pts
        sets.append([Curve(periods[:n], c0[:n] + rng.normal(0, 0.005, n),
                           mode=0, weight=1.0,
                           uncertainty=0.01 * np.ones(n))])
    if ragged and sets:
        p1 = np.linspace(0.05, 0.12, 4)
        c1 = np.asarray(phase_velocity(jnp.asarray(p1), _truth_model(),
                                       mode=1, n_grid=400), dtype=np.float64)
        sets[0].append(Curve(p1, c1, mode=1, weight=0.5,
                             uncertainty=0.02 * np.ones(4)))
    return sets


@pytest.fixture(scope="module")
def small_fleet():
    """(spec, curve_sets, FleetResult) — the one tier-1 fleet compile."""
    spec = _three_layer_spec()
    sets = _curve_sets(3, ragged=True)
    res = invert_fleet(spec, sets, seed=0, **BUDGET)
    return spec, sets, res


class TestPackCurveSets:
    def test_padding_and_segments(self):
        sets = _curve_sets(3, ragged=True)
        cb = pack_curve_sets(sets)
        assert cb.n_targets == 3
        npts = [sum(len(c.period) for c in cs) for cs in sets]
        assert cb.period.shape[1] == max(npts)
        for t, n in enumerate(npts):
            assert int(cb.valid[t].sum()) == n
        # target 0 carries two curves -> two segment ids, weighted sum
        assert int(cb.segment[0].max()) == 1
        assert float(cb.wsum[0]) == pytest.approx(1.5)
        # pad points are inert defaults (period 1, unc 1, weight row 0)
        pad = ~np.asarray(cb.valid[1])
        assert np.all(np.asarray(cb.period[1])[pad] == 1.0)

    def test_capacity_pinning_and_errors(self):
        sets = _curve_sets(2)
        cb = pack_curve_sets(sets, max_points=40, max_curves=3)
        assert cb.period.shape == (2, 40) and cb.weight.shape == (2, 3)
        with pytest.raises(ValueError, match="capacity"):
            pack_curve_sets(sets, max_points=4)
        with pytest.raises(ValueError):
            pack_curve_sets([])

    def test_fixed_capacity_means_fixed_shapes(self):
        a = pack_curve_sets(_curve_sets(2), max_points=30, max_curves=2)
        b = pack_curve_sets(_curve_sets(2, ragged=True), max_points=30,
                            max_curves=2)
        assert a.period.shape == b.period.shape


class TestPackedMisfitParity:
    """The packed masked misfit IS the closure oracle, pointwise."""

    @pytest.mark.parametrize("invalid", ["penalty", "truncate"])
    def test_matches_closure_on_ragged_sets(self, invalid):
        spec = _three_layer_spec()
        sets = _curve_sets(3, ragged=True)
        cb = pack_curve_sets(sets)
        packed = make_packed_misfit_fn(spec, n_grid=120, invalid=invalid)
        rng = np.random.default_rng(3)
        xs = jnp.asarray(rng.uniform(0.05, 0.95, (4, spec.n_params)))
        for t, cs in enumerate(sets):
            closure = make_misfit_fn(spec, cs, n_grid=120, invalid=invalid)
            data_t = jax.tree.map(lambda a: a[t], cb)
            for x in xs:
                np.testing.assert_allclose(float(packed(x, data_t)),
                                           float(closure(x)),
                                           rtol=1e-10, atol=1e-12)

    def test_matches_closure_at_parity_best_models(self):
        """Evaluate both misfits at the committed INVERSION_PARITY.json
        ``x_best`` vectors — the exact models whose misfits are pinned —
        on synthetic reference-band curve sets (the reference ridge data
        is not shipped; pointwise agreement at the committed points is the
        contract that transfers)."""
        with open(os.path.join(_REPO, "INVERSION_PARITY.json")) as f:
            parity = json.load(f)
        cases = {"speed": speed_model_spec(), "weight": weight_model_spec()}
        periods = np.asarray(1.0 / np.arange(2.0, 24.0, 1.5))[::-1]
        for spec_name, spec in cases.items():
            xs = [e["x_best"] for k, e in parity.items()
                  if k.endswith(spec_name) and "x_best" in e][:2]
            assert xs, f"no committed x_best for {spec_name}"
            ref = spec.to_model(jnp.full(spec.n_params, 0.5))
            vel = np.asarray(phase_velocity(jnp.asarray(periods), ref,
                                            mode=0, n_grid=300))
            keep = np.isfinite(vel)
            curves = [Curve(periods[keep], vel[keep], mode=0, weight=1.0,
                            uncertainty=0.02 * np.ones(keep.sum()))]
            closure = make_misfit_fn(spec, curves, n_grid=300)
            packed = make_packed_misfit_fn(spec, n_grid=300)
            data = jax.tree.map(lambda a: a[0], pack_curve_sets([curves]))
            for x in xs:
                x = jnp.asarray(np.asarray(x, np.float64))
                np.testing.assert_allclose(float(packed(x, data)),
                                           float(closure(x)),
                                           rtol=1e-10, atol=1e-12)


class TestFleetResult:
    def test_credible_intervals_ship_for_every_target(self, small_fleet):
        _, sets, res = small_fleet
        T = len(sets)
        n_layers = 3
        assert res.vs.shape == (T, n_layers)
        assert res.vs_lo.shape == res.vs_hi.shape == (T, n_layers)
        assert np.all(res.vs_lo <= res.vs) and np.all(res.vs <= res.vs_hi)
        assert np.all(res.n_ensemble >= 1)
        assert np.all(np.isfinite(res.misfit))
        # convergence history is monotone non-increasing per target
        assert np.all(np.diff(res.history, axis=1) <= 1e-12)

    def test_uncertainty_never_loosens_misfit(self, small_fleet):
        """The reported per-target misfit IS the closure oracle's score of
        the reported best model — intervals annotate, never loosen."""
        spec, sets, res = small_fleet
        for t, cs in enumerate(sets):
            oracle = float(make_misfit_fn(spec, cs, n_grid=120)(
                jnp.asarray(res.x_best[t])))
            np.testing.assert_allclose(res.misfit[t], oracle,
                                       rtol=1e-9, atol=1e-12)
            # and the ensemble members never beat the reported best
            assert res.misfit[t] <= np.nanmin(res.misfits[t]) + 1e-12

    def test_steady_state_zero_retrace(self, small_fleet):
        """Same fleet shape again -> ZERO fresh jaxpr traces (the
        one-program contract's steady state; the full T=1/3/5 invariance
        protocol is the slow test below)."""
        from das_diff_veh_tpu.obs import xla_events
        from das_diff_veh_tpu.obs.registry import MetricsRegistry
        spec, sets, _ = small_fleet
        reg = MetricsRegistry()
        watch = xla_events.install(reg)
        try:
            invert_fleet(spec, sets, seed=0, **BUDGET)
        finally:
            xla_events.uninstall(reg)
        assert watch.traces == 0


class TestChangeDetection:
    def _shift(self, res, t, layer, delta):
        vs = res.vs.copy()
        vs[t, layer] += delta
        return res._replace(vs=vs, vs_lo=vs - (res.vs - res.vs_lo),
                            vs_hi=vs + (res.vs_hi - res.vs))

    def test_detect_vs_shifts_events(self, small_fleet):
        _, _, res = small_fleet
        assert detect_vs_shifts(res, res) == []
        big = float(res.vs_hi[1, 0] - res.vs[1, 0]) + 0.05
        events = detect_vs_shifts(res, self._shift(res, 1, 0, big))
        assert [(e.target, e.layer) for e in events] == [(1, 0)]
        # a within-interval wiggle is NOT an event
        small = float(res.vs_hi[1, 0] - res.vs[1, 0]) * 0.5
        assert detect_vs_shifts(res, self._shift(res, 1, 0, small)) == []

    def test_monitor_raises_counter_alarm_and_flight(self, small_fleet):
        from das_diff_veh_tpu.obs.flight import FlightRecorder
        from das_diff_veh_tpu.obs.registry import MetricsRegistry
        from das_diff_veh_tpu.pipeline.timelapse import FleetVsMonitor
        _, _, res = small_fleet
        reg = MetricsRegistry()
        fl = FlightRecorder(capacity=8)
        mon = FleetVsMonitor(res, registry=reg, flight=fl,
                             target_names=["t0", "t1", "t2"])
        assert mon.observe(res) == []
        assert reg.get("das_fleet_vs_alarm_active").labels(
            target="t1").value == 0.0
        big = float(res.vs_hi[1, 0] - res.vs[1, 0]) + 0.05
        events = mon.observe(self._shift(res, 1, 0, big))
        assert len(events) == 1
        assert reg.get("das_fleet_vs_shift_total").labels(
            target="t1").value == 1.0
        assert reg.get("das_fleet_vs_alarm_active").labels(
            target="t1").value == 1.0
        assert reg.get("das_fleet_vs_alarm_active").labels(
            target="t0").value == 0.0
        recs = [r for r in fl.records() if r["kind"] == "vs_shift"]
        assert len(recs) == 1 and recs[0]["target"] == "t1"
        # recovery clears the alarm; rebase adopts a new baseline
        mon.observe(res)
        assert reg.get("das_fleet_vs_alarm_active").labels(
            target="t1").value == 0.0
        shifted = self._shift(res, 1, 0, big)
        mon.rebase(shifted)
        assert mon.observe(shifted) == []


@pytest.mark.slow
class TestFleetSlow:
    """Multi-compile-set contracts: each distinct (T, tc) shape pays its
    own compile on this 1-core host, so these ride the slow marker."""

    def test_one_program_contract_trace_invariance(self):
        """Fresh fleets of T=1, 3, and 5 targets trace the SAME number of
        XLA programs, and a repeated shape traces zero."""
        from das_diff_veh_tpu.obs import xla_events
        from das_diff_veh_tpu.obs.registry import MetricsRegistry
        spec = _three_layer_spec()
        sets = _curve_sets(5)
        # warm-up: first-touch scaffolding jits (shape-independent jnp
        # internals) are traced once per process, not per fleet
        invert_fleet(spec, sets[:2], seed=0, **BUDGET)

        def traced(ss):
            reg = MetricsRegistry()
            watch = xla_events.install(reg)
            try:
                invert_fleet(spec, ss, seed=0, **BUDGET)
            finally:
                xla_events.uninstall(reg)
            return watch.traces

        t1, t3, t5, t3b = (traced(sets[:1]), traced(sets[:3]),
                           traced(sets[:5]), traced(sets[:3]))
        assert t1 == t3 == t5, (t1, t3, t5)
        assert t3b == 0

    def test_fleet_reproduces_per_target_multirun(self):
        """Seeding contract: fleet target t == invert_multirun with
        seed + t*n_runs on the same curves (same init, same chunk
        stream)."""
        spec = _three_layer_spec()
        sets = _curve_sets(2)
        res = invert_fleet(spec, sets, seed=7, **BUDGET)
        for t, cs in enumerate(sets):
            single = invert_multirun(spec, cs,
                                     seed=7 + t * BUDGET["n_runs"], **BUDGET)
            np.testing.assert_allclose(res.misfit[t], float(single.misfit),
                                       rtol=1e-9)
            np.testing.assert_allclose(res.x_best[t],
                                       np.asarray(single.x_best), atol=1e-7)

    def test_target_chunk_invariance(self):
        """Chunked and unchunked fleets agree (chunk padding is inert)."""
        spec = _three_layer_spec()
        sets = _curve_sets(5)
        base = invert_fleet(spec, sets, seed=0, **BUDGET)
        chunked = invert_fleet(spec, sets, seed=0, target_chunk=2, **BUDGET)
        np.testing.assert_allclose(chunked.misfit, base.misfit, rtol=5e-3)
        np.testing.assert_allclose(chunked.x_best, base.x_best, atol=1e-6)

    @pytest.mark.parallel
    def test_sharded_matches_unsharded(self):
        """Mesh-sharded target axis agrees with the single-device fleet
        within the established test_inversion tolerance."""
        mesh = jax.make_mesh((8,), ("win",))
        spec = _three_layer_spec()
        sets = _curve_sets(5)
        base = invert_fleet(spec, sets, seed=0, **BUDGET)
        sharded = invert_fleet(spec, sets, seed=0, mesh=mesh, **BUDGET)
        np.testing.assert_allclose(sharded.misfit, base.misfit, rtol=5e-3)
        np.testing.assert_allclose(sharded.x_best, base.x_best, atol=1e-7)
        np.testing.assert_allclose(sharded.vs, base.vs, atol=1e-6)
