"""Pallas tiled all-pairs xcorr: parity against the reference-semantics
einsum path (ops/xcorr.py xcorr_vshot_batch) and internal consistency of
the streamed variants.  The kernel itself runs in interpreter mode here
(CPU CI); the real-TPU path is exercised by bench.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from das_diff_veh_tpu.ops.pallas_xcorr import (_window_spectra,
                                               peak_from_spectra,
                                               xcorr_all_pairs,
                                               xcorr_all_pairs_peak)
from das_diff_veh_tpu.ops.xcorr import xcorr_vshot_batch

RNG = np.random.default_rng(5)


def _data(nch=12, nt=400):
    return jnp.asarray(RNG.standard_normal((nch, nt)), jnp.float32)


def test_all_pairs_matches_vshot_batch():
    d = _data()
    wlen = 100
    ref = np.asarray(xcorr_vshot_batch(d, wlen))
    got = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    np.testing.assert_allclose(got, ref, rtol=1e-4,
                               atol=1e-5 * np.abs(ref).max())


def test_pallas_kernel_matches_einsum_path():
    d = _data(nch=20, nt=300)
    wlen = 64
    a = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    b = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=True, interpret=True))
    np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)


def test_source_chunking_is_transparent():
    d = _data(nch=13, nt=320)
    wlen = 64
    whole = np.asarray(xcorr_all_pairs(d, wlen, src_chunk=64,
                                       use_pallas=False))
    chunked = np.asarray(xcorr_all_pairs(d, wlen, src_chunk=4,
                                         use_pallas=False))
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-6)


def test_lag_trim_matches_center_slice():
    d = _data(nch=8, nt=300)
    wlen, keep = 80, 11
    full = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    trimmed = np.asarray(xcorr_all_pairs(d, wlen, lag_keep=keep,
                                         use_pallas=False))
    mid = wlen // 2
    np.testing.assert_allclose(trimmed, full[..., mid - keep:mid + keep + 1],
                               atol=1e-7)


def test_peak_reduction_matches_full():
    d = _data(nch=9, nt=256)
    wlen = 64
    full = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    peak = np.asarray(xcorr_all_pairs_peak(d, wlen, src_chunk=4,
                                           use_pallas=False))
    np.testing.assert_allclose(peak, np.abs(full).max(-1), rtol=1e-6,
                               atol=1e-7)


def test_win_block_streaming_matches_unblocked():
    """Long-record path: accumulating window-mean cross-spectra win_block
    windows at a time is exactly the full-window mean (linearity), incl.
    a block count that does not divide nwin (zero-padded windows)."""
    d = _data(nch=9, nt=1200)           # wlen 64, 50% overlap -> 36 windows
    wlen = 64
    want = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                           win_block=None))
    for wb in (5, 8, 36, 100):          # ragged, even, ==nwin, >nwin
        got = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                              win_block=wb, src_chunk=4))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_win_block_pallas_interpret():
    d = _data(nch=10, nt=900)
    wlen = 64
    want = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    got = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                          interpret=True, win_block=8,
                                          src_chunk=4))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_win_block_auto_engages_past_threshold():
    """Past WIN_BLOCK_AUTO windows the blocked accumulation kicks in by
    default and still matches an explicitly unblocked run."""
    from das_diff_veh_tpu.ops.pallas_xcorr import WIN_BLOCK_AUTO

    d = _data(nch=6, nt=(WIN_BLOCK_AUTO + 2) * 16 + 16)   # 50-51 windows
    wlen = 32
    auto = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    explicit = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                               win_block=10 ** 6))
    np.testing.assert_allclose(auto, explicit, rtol=2e-5, atol=1e-6)


def test_lag_domain_win_block_matches_unstreamed():
    """The lag-domain path streams the window axis too: blocked accumulation
    (incl. a ragged tail) must reproduce the unstreamed result exactly."""
    d = _data(nch=8, nt=1200)           # wlen 64, 50% overlap -> 36 windows
    wlen = 64
    want = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    for wb in (5, 8, 36, 100):          # ragged, even, ==nwin, >nwin
        got = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False,
                                         win_block=wb, src_chunk=4))
        np.testing.assert_allclose(got, want, rtol=2e-5,
                                   atol=1e-5 * np.abs(want).max())


def test_lag_domain_win_block_pallas_interpret():
    """Kernel-grid window streaming on the lag-domain path (ragged tail
    masked in-kernel) vs the unstreamed einsum reference."""
    d = _data(nch=10, nt=900)           # 27 windows: 27 % 8 = 3 ragged tail
    wlen = 64
    want = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    got = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=True,
                                     interpret=True, win_block=8,
                                     src_chunk=4))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_x64_spectra_blocked_accumulator_dtype():
    """x64-enabled callers can feed complex128 spectra straight into the
    blocked path: the fori_loop accumulator derives its dtype from the
    inputs (a hardcoded complex64 carry used to raise a dtype mismatch)."""
    d = _data(nch=6, nt=640)
    wlen = 64
    wf = _window_spectra(d, wlen, 0.5).astype(jnp.complex128)
    assert wf.dtype == jnp.complex128   # conftest enables x64
    got = np.asarray(peak_from_spectra(wf, wf, wlen, 4, False, win_block=5))
    want = np.asarray(peak_from_spectra(wf, wf, wlen, 4, False,
                                        win_block=None))
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-13)


def test_negative_win_block_rejected():
    d = _data(nch=6, nt=300)
    wf = _window_spectra(d, 64, 0.5)
    with pytest.raises(ValueError, match="win_block"):
        peak_from_spectra(wf, wf, 64, 4, False, win_block=-1)
    with pytest.raises(ValueError, match="win_block"):
        xcorr_all_pairs_peak(d, 64, use_pallas=False, win_block=-3)
    with pytest.raises(ValueError, match="win_block"):
        xcorr_all_pairs(d, 64, use_pallas=False, win_block=-1)


def test_no_window_axis_pad_in_blocked_paths():
    """Acceptance: no full zero-padded copy of wf_all (or wf_src) along the
    window axis remains in the blocked path — asserted on the traced
    program of both the einsum and the Pallas variants (the walker lives in
    jaxpr_checks.py, shared with the parallel no-broadcast pins)."""
    from jaxpr_checks import window_axis_pads

    d = _data(nch=10, nt=900)           # 27 windows, win_block 8: ragged
    wlen = 64
    wf = _window_spectra(d, wlen, 0.5)
    nwin = wf.shape[1]
    assert nwin % 8 != 0                # the ragged case is the hard one

    for use_pallas in (False, True):
        jx = jax.make_jaxpr(
            lambda ws, wa: peak_from_spectra(ws, wa, wlen, 4, use_pallas,
                                             interpret=True, win_block=8)
        )(wf, wf)
        pads = window_axis_pads(jx, nwin)
        assert not pads, f"window-axis pad survives (pallas={use_pallas}): {pads}"


def test_long_record_auto_streams_interpret():
    """Interpret-mode long-record smoke test: past WIN_BLOCK_AUTO windows the
    kernel-grid streaming engages automatically (ragged tail included) and
    matches the unstreamed einsum reference."""
    from das_diff_veh_tpu.ops.pallas_xcorr import (WIN_BLOCK_AUTO,
                                                   _WIN_BLOCK_DEFAULT)

    wlen = 64
    nt = 64 * (WIN_BLOCK_AUTO + 14)     # 121 windows > auto threshold
    d = _data(nch=6, nt=nt)
    nwin = (nt - wlen) // (wlen // 2) + 1
    assert nwin > WIN_BLOCK_AUTO and nwin % _WIN_BLOCK_DEFAULT != 0
    want = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                           win_block=nwin))
    got = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                          interpret=True, src_chunk=4))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_long_record_streamed_bench_scale():
    """Bench-scale (nt ~ 60k) streamed sweep on the CPU einsum path — the
    shape bench.py's BENCH long-record entry runs on-chip.  Excluded from
    tier-1 by the ``slow`` marker; ``pytest -m slow`` runs the full sweep."""
    rng = np.random.default_rng(17)
    d = jnp.asarray(rng.standard_normal((48, 61440)).astype(np.float32))
    wlen = 1024                          # 119 windows, ragged vs 32-block
    peak = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                           src_chunk=16))
    unstreamed = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                                 win_block=10 ** 6,
                                                 src_chunk=16))
    np.testing.assert_allclose(peak, unstreamed, rtol=2e-5, atol=1e-6)


def test_fused_lagmax_matches_unfused_bitwise():
    """The fused peak finish (blockwise irfft + Pallas lag-streaming
    abs-max) must equal the unfused XLA finish bit-for-bit on identical
    cross-spectra — max is order-independent and the row-wise irfft is the
    same transform, so any drift here is a real kernel bug.  Covers the
    single-pass (block >= nall), blocked-even, and blocked-ragged
    (nall % block != 0) shapes."""
    d = _data(nch=10, nt=900)
    wlen = 64
    unfused = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                              interpret=True, src_chunk=4,
                                              lagmax_block=0))
    for lb in (None, 4, 5, 100):        # auto, ragged, even-ish, >= nall
        fused = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                                interpret=True, src_chunk=4,
                                                lagmax_block=lb))
        np.testing.assert_array_equal(fused, unfused)


def test_fused_lagmax_einsum_path_opt_in():
    """lagmax_block > 0 fuses the finish on the einsum fallback too (the
    default there stays the exact XLA finish), and works WITHOUT the
    caller passing interpret: the reduction kernel only lowers on TPU, so
    on other backends the fused finish drops to interpret mode itself
    instead of failing in pallas_call."""
    d = _data(nch=9, nt=700)
    wlen = 64
    want = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    got = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                          lagmax_block=4))
    np.testing.assert_array_equal(got, want)


def test_negative_lagmax_block_rejected():
    d = _data(nch=6, nt=300)
    with pytest.raises(ValueError, match="lagmax_block"):
        xcorr_all_pairs_peak(d, 64, use_pallas=False, lagmax_block=-1)


def test_pallas_peak_interpret():
    d = _data(nch=10, nt=256)
    wlen = 64
    a = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    b = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                        interpret=True, src_chunk=4))
    np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)


def test_sharded_all_pairs_matches_single_device():
    # 8-virtual-device CPU mesh; 26 channels exercises the pad/trim path
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(4)
    data = jnp.asarray(rng.standard_normal((26, 512)).astype(np.float32))
    mesh = make_mesh(8)
    got = np.asarray(sharded_all_pairs_peak(data, 128, mesh,
                                            use_pallas=False))
    want = np.asarray(xcorr_all_pairs_peak(data, 128, use_pallas=False))
    assert got.shape == (26, 26)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_sharded_all_pairs_pallas_interpret():
    """ADVICE r3: the Pallas kernel path under shard_map was never
    exercised — run it in interpret mode on the CPU mesh and require
    equality with the unsharded einsum path."""
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(11)
    data = jnp.asarray(rng.standard_normal((26, 256)).astype(np.float32))
    mesh = make_mesh(8)
    got = np.asarray(sharded_all_pairs_peak(data, 64, mesh, use_pallas=True,
                                            interpret=True, src_chunk=4))
    want = np.asarray(xcorr_all_pairs_peak(data, 64, use_pallas=False))
    assert got.shape == (26, 26)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_decide_pallas_uses_per_device_rows():
    """The sharded path's kernel-vs-einsum heuristic keys on the per-device
    source-row count, not the global channel count."""
    import jax

    from das_diff_veh_tpu.ops.pallas_xcorr import PALLAS_MIN_CH, _decide_pallas

    # single-device semantics unchanged
    assert _decide_pallas(PALLAS_MIN_CH, None) == \
        (jax.default_backend() not in ("cpu",))
    assert _decide_pallas(PALLAS_MIN_CH - 1, None) is False
    # sharded: global nch >= threshold but 8-way shards fall below it
    nch, n_dev = PALLAS_MIN_CH, 8
    assert _decide_pallas(nch // n_dev, None) is False
