"""Pallas tiled all-pairs xcorr: parity against the reference-semantics
einsum path (ops/xcorr.py xcorr_vshot_batch) and internal consistency of
the streamed variants.  The kernel itself runs in interpreter mode here
(CPU CI); the real-TPU path is exercised by bench.py."""

import numpy as np
import jax.numpy as jnp

from das_diff_veh_tpu.ops.pallas_xcorr import (xcorr_all_pairs,
                                               xcorr_all_pairs_peak)
from das_diff_veh_tpu.ops.xcorr import xcorr_vshot_batch

RNG = np.random.default_rng(5)


def _data(nch=12, nt=400):
    return jnp.asarray(RNG.standard_normal((nch, nt)), jnp.float32)


def test_all_pairs_matches_vshot_batch():
    d = _data()
    wlen = 100
    ref = np.asarray(xcorr_vshot_batch(d, wlen))
    got = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    np.testing.assert_allclose(got, ref, rtol=1e-4,
                               atol=1e-5 * np.abs(ref).max())


def test_pallas_kernel_matches_einsum_path():
    d = _data(nch=20, nt=300)
    wlen = 64
    a = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    b = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=True, interpret=True))
    np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)


def test_source_chunking_is_transparent():
    d = _data(nch=13, nt=320)
    wlen = 64
    whole = np.asarray(xcorr_all_pairs(d, wlen, src_chunk=64,
                                       use_pallas=False))
    chunked = np.asarray(xcorr_all_pairs(d, wlen, src_chunk=4,
                                         use_pallas=False))
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-6)


def test_lag_trim_matches_center_slice():
    d = _data(nch=8, nt=300)
    wlen, keep = 80, 11
    full = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    trimmed = np.asarray(xcorr_all_pairs(d, wlen, lag_keep=keep,
                                         use_pallas=False))
    mid = wlen // 2
    np.testing.assert_allclose(trimmed, full[..., mid - keep:mid + keep + 1],
                               atol=1e-7)


def test_peak_reduction_matches_full():
    d = _data(nch=9, nt=256)
    wlen = 64
    full = np.asarray(xcorr_all_pairs(d, wlen, use_pallas=False))
    peak = np.asarray(xcorr_all_pairs_peak(d, wlen, src_chunk=4,
                                           use_pallas=False))
    np.testing.assert_allclose(peak, np.abs(full).max(-1), rtol=1e-6,
                               atol=1e-7)


def test_win_block_streaming_matches_unblocked():
    """Long-record path: accumulating window-mean cross-spectra win_block
    windows at a time is exactly the full-window mean (linearity), incl.
    a block count that does not divide nwin (zero-padded windows)."""
    d = _data(nch=9, nt=1200)           # wlen 64, 50% overlap -> 36 windows
    wlen = 64
    want = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                           win_block=None))
    for wb in (5, 8, 36, 100):          # ragged, even, ==nwin, >nwin
        got = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                              win_block=wb, src_chunk=4))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_win_block_pallas_interpret():
    d = _data(nch=10, nt=900)
    wlen = 64
    want = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    got = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                          interpret=True, win_block=8,
                                          src_chunk=4))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_win_block_auto_engages_past_threshold():
    """Past WIN_BLOCK_AUTO windows the blocked accumulation kicks in by
    default and still matches an explicitly unblocked run."""
    from das_diff_veh_tpu.ops.pallas_xcorr import WIN_BLOCK_AUTO

    d = _data(nch=6, nt=(WIN_BLOCK_AUTO + 2) * 16 + 16)   # 50-51 windows
    wlen = 32
    auto = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    explicit = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False,
                                               win_block=10 ** 6))
    np.testing.assert_allclose(auto, explicit, rtol=2e-5, atol=1e-6)


def test_pallas_peak_interpret():
    d = _data(nch=10, nt=256)
    wlen = 64
    a = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=False))
    b = np.asarray(xcorr_all_pairs_peak(d, wlen, use_pallas=True,
                                        interpret=True, src_chunk=4))
    np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)


def test_sharded_all_pairs_matches_single_device():
    # 8-virtual-device CPU mesh; 26 channels exercises the pad/trim path
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(4)
    data = jnp.asarray(rng.standard_normal((26, 512)).astype(np.float32))
    mesh = make_mesh(8)
    got = np.asarray(sharded_all_pairs_peak(data, 128, mesh,
                                            use_pallas=False))
    want = np.asarray(xcorr_all_pairs_peak(data, 128, use_pallas=False))
    assert got.shape == (26, 26)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_sharded_all_pairs_pallas_interpret():
    """ADVICE r3: the Pallas kernel path under shard_map was never
    exercised — run it in interpret mode on the CPU mesh and require
    equality with the unsharded einsum path."""
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(11)
    data = jnp.asarray(rng.standard_normal((26, 256)).astype(np.float32))
    mesh = make_mesh(8)
    got = np.asarray(sharded_all_pairs_peak(data, 64, mesh, use_pallas=True,
                                            interpret=True, src_chunk=4))
    want = np.asarray(xcorr_all_pairs_peak(data, 64, use_pallas=False))
    assert got.shape == (26, 26)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_decide_pallas_uses_per_device_rows():
    """The sharded path's kernel-vs-einsum heuristic keys on the per-device
    source-row count, not the global channel count."""
    import jax

    from das_diff_veh_tpu.ops.pallas_xcorr import PALLAS_MIN_CH, _decide_pallas

    # single-device semantics unchanged
    assert _decide_pallas(PALLAS_MIN_CH, None) == \
        (jax.default_backend() not in ("cpu",))
    assert _decide_pallas(PALLAS_MIN_CH - 1, None) is False
    # sharded: global nch >= threshold but 8-way shards fall below it
    nch, n_dev = PALLAS_MIN_CH, 8
    assert _decide_pallas(nch // n_dev, None) is False
