import numpy as np
import pytest

from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io import readers, segy
from das_diff_veh_tpu.io.synthetic import (SceneConfig, dispersive_shot,
                                           synthesize_section)


def test_npz_roundtrip(tmp_path):
    sec = DasSection(np.random.randn(8, 100), np.arange(8.0), np.arange(100) * 0.004)
    p = str(tmp_path / "20230101_000000.npz")
    readers.save_section_npz(p, sec)
    back = readers.read_npz_section(p, cut_taper=False)
    np.testing.assert_allclose(back.data, sec.data)
    np.testing.assert_allclose(back.x, sec.x)


def test_npz_channel_range_and_taper(tmp_path):
    nt = 120
    t = (np.arange(nt) - 10) * 0.004      # taper pad: 10 samples, t crosses zero at idx 10
    sec = DasSection(np.random.randn(16, nt), np.arange(400.0, 416.0), t)
    p = str(tmp_path / "a.npz")
    readers.save_section_npz(p, sec)
    back = readers.read_npz_section(p, ch1=404, ch2=410)
    assert back.data.shape == (6, nt - 20)
    assert back.x[0] == 404


def test_segy_roundtrip(tmp_path):
    data = np.random.randn(12, 250).astype(np.float32)
    p = str(tmp_path / "a.segy")
    segy.write_segy(p, data, dt=0.004)
    back, dt, ns = segy.read_segy(p)
    assert ns == 250 and abs(dt - 0.004) < 1e-9
    np.testing.assert_allclose(back, data, rtol=1e-6)
    sub, _, _ = segy.read_segy(p, ch1=2, ch2=5)
    np.testing.assert_allclose(sub, data[2:5], rtol=1e-6)


def test_segy_ibm_float():
    # 0x42640000 = +100.0 in IBM hex float
    raw = np.array([0x42640000, 0xC2640000, 0x41100000], dtype=np.uint32)
    vals = segy._ibm_to_float(raw)
    np.testing.assert_allclose(vals, [100.0, -100.0, 1.0])


def test_multi_file_concat(tmp_path):
    dt = 0.004
    s1 = DasSection(np.ones((4, 50)), np.arange(4.0), np.arange(50) * dt)
    s2 = DasSection(2 * np.ones((4, 60)), np.arange(4.0), np.arange(60) * dt)
    p1, p2 = str(tmp_path / "x1.npz"), str(tmp_path / "x2.npz")
    readers.save_section_npz(p1, s1)
    readers.save_section_npz(p2, s2)
    out = readers.read_sections([p1, p2], cut_taper=False)
    assert out.data.shape == (4, 110)
    # time axis continues across the file boundary
    assert out.t[50] == pytest.approx(50 * dt)


def test_directory_dataset(tmp_path):
    d = tmp_path / "20230101"
    d.mkdir()
    for h in (0, 1):
        sec = DasSection(np.random.randn(8, 100), np.arange(400.0, 408.0),
                         np.arange(100) * 0.004)
        readers.save_section_npz(str(d / f"20230101_0{h}0000.npz"), sec)
    ds = readers.DirectoryDataset("20230101", root=str(tmp_path), ch1=400, ch2=408,
                                  smoothing=False)
    assert len(ds) == 2
    assert ds.time_interval() == 3600.0
    sec = ds[0]
    assert sec.data.shape[0] == 8


def test_synthetic_scene_shapes_and_truth():
    cfg = SceneConfig(nch=48, duration=60.0, n_vehicles=3, seed=1)
    sec, truth = synthesize_section(cfg)
    assert sec.data.shape == (48, 15000)
    assert truth.speed.shape == (3,)
    # quasi-static deflection is negative near each vehicle's arrival
    x = np.asarray(sec.x)
    t_arr = truth.arrival_times(x)
    v, ch = 0, 20
    ti = int(round(t_arr[v, ch] * cfg.fs))
    if 0 <= ti < sec.data.shape[1]:
        assert sec.data[ch, ti] < 0


def test_dispersive_shot_moveout():
    # far channel peaks later than near channel
    d = dispersive_shot(nx=32, nt=2000, dx=8.16, dt=0.004)
    p_near = np.argmax(np.abs(d[1]))
    p_far = np.argmax(np.abs(d[30]))
    assert p_far > p_near


def test_cut_time_nearest_sample():
    from das_diff_veh_tpu.core.section import DasSection
    t = np.arange(1000) / 250.0
    data = np.arange(3000, dtype=float).reshape(3, 1000)
    sec = DasSection(data, np.arange(3.0), t).cut_time(0.5012, 2.0)
    # nearest-index semantics of the reference cut_data_along_time
    assert sec.t[0] == t[125] and sec.t.shape[0] == 500 - 125
    np.testing.assert_allclose(np.asarray(sec.data), data[:, 125:500])


class TestSegyAdversarial:
    """Hand-built SEG-Y fixtures beyond the writer's own output (VERDICT r3
    weak #6: the roundtrip test can only prove self-consistency)."""

    @staticmethod
    def _build(fmt, ns, payloads, dt_us=4000, extra_bytes=0):
        """Raw SEG-Y bytes: headers + given per-trace payload bytes."""
        binh = bytearray(400)
        binh[16:18] = int(dt_us).to_bytes(2, "big")
        binh[20:22] = int(ns).to_bytes(2, "big")
        binh[24:26] = int(fmt).to_bytes(2, "big")
        out = b"\x40" * 3200 + bytes(binh)        # EBCDIC spaces text header
        for p in payloads:
            out += bytes(240) + p
        return out + b"\x00" * extra_bytes

    def test_ibm_float_format1_known_words(self, tmp_path):
        # classic IBM/360 encodings: -118.625 = 0xC276A000, 1.0 = 0x41100000,
        # 0.15625 = 0x40280000, 0.0 = 0x00000000
        import struct
        words = [0xC276A000, 0x41100000, 0x40280000, 0x00000000]
        payload = b"".join(struct.pack(">I", w) for w in words)
        p = tmp_path / "ibm.sgy"
        p.write_bytes(self._build(1, 4, [payload, payload]))
        from das_diff_veh_tpu.io.segy import read_segy
        data, dt, ns = read_segy(str(p))
        assert (data.shape, ns, dt) == ((2, 4), 4, 0.004)
        np.testing.assert_allclose(data[0], [-118.625, 1.0, 0.15625, 0.0],
                                   rtol=1e-7)

    def test_format5_odd_ns_and_trailing_partial_trace(self, tmp_path):
        # ns=7 (odd) + 13 junk bytes after the last trace: the partial
        # "trace" must be dropped, complete traces parsed exactly
        tr = [(np.arange(7) + i).astype(">f4") for i in range(3)]
        p = tmp_path / "odd.sgy"
        p.write_bytes(self._build(5, 7, [t.tobytes() for t in tr],
                                  extra_bytes=13))
        from das_diff_veh_tpu.io.segy import read_segy
        data, dt, ns = read_segy(str(p))
        assert data.shape == (3, 7)
        np.testing.assert_array_equal(data, np.stack([t.astype(np.float32)
                                                      for t in tr]))

    def test_int16_format3(self, tmp_path):
        tr = np.array([-32768, -1, 0, 1, 32767], dtype=">i2")
        p = tmp_path / "i16.sgy"
        p.write_bytes(self._build(3, 5, [tr.tobytes()]))
        from das_diff_veh_tpu.io.segy import read_segy
        data, _, _ = read_segy(str(p))
        np.testing.assert_array_equal(data[0],
                                      tr.astype(np.float32))

    def test_loud_failures(self, tmp_path):
        from das_diff_veh_tpu.io.segy import read_segy
        cases = {
            "fmt4.sgy": (self._build(4, 5, []), "format code 4"),
            "ns0.sgy": (self._build(5, 0, []), "0 samples"),
            "dt0.sgy": (self._build(5, 5, [], dt_us=0), "0 us sample"),
            "trunc.sgy": (b"\x00" * 100, "truncated"),
        }
        for name, (raw, msg) in cases.items():
            p = tmp_path / name
            p.write_bytes(raw)
            with pytest.raises(ValueError, match=msg):
                read_segy(str(p))
