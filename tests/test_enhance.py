"""CLAHE f-v enhancement vs the real cv2 pipeline the reference uses
(modules/utils.py:613-619: CLAHE(clip 100, tiles (100,10)) + 10x10 blur).

cv2's CLAHE interpolation runs in fixed-point, so individual pixels can
differ by a few gray levels; the assertions bound mean and tail error, and
the box blur (which the reference always applies after) is checked to
+-1 level.
"""

import jax.numpy as jnp
import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from das_diff_veh_tpu.ops.enhance import box_blur_u8, clahe_u8, fv_map_enhance

RNG = np.random.default_rng(7)


def test_clahe_matches_cv2():
    img = (np.abs(RNG.standard_normal((200, 121))) * 60).clip(0, 255).astype(np.uint8)
    ref = cv2.createCLAHE(clipLimit=100.0, tileGridSize=(20, 5)).apply(img)
    got = np.asarray(clahe_u8(jnp.asarray(img.astype(np.int32)), 100.0, (20, 5)))
    d = np.abs(ref.astype(int) - got)
    assert d.mean() < 2.0, d.mean()
    assert (d > 5).mean() < 0.02, (d > 5).mean()


def test_box_blur_matches_cv2():
    img = RNG.integers(0, 256, size=(120, 90)).astype(np.uint8)
    ref = cv2.blur(img, (10, 10))
    got = np.asarray(box_blur_u8(jnp.asarray(img.astype(np.int32)), 10))
    assert np.abs(ref.astype(int) - got).max() <= 1


def test_full_enhance_matches_reference_pipeline():
    # the exact reference chain (utils.py:613-619) on a dispersion-like map
    fv = np.abs(RNG.standard_normal((250, 121))).astype(np.float64) + 0.05
    fvn = (fv - fv.min()) / fv.max()
    u8 = np.array(fvn * 255, dtype=np.uint8)
    clahe = cv2.createCLAHE(clipLimit=100.0, tileGridSize=(25, 5))
    ref = cv2.blur(clahe.apply(u8), (10, 10))

    got = np.asarray(fv_map_enhance(jnp.asarray(fv), 100.0, (25, 5), 10))
    d = np.abs(ref.astype(int) - got)
    assert d.max() <= 6, d.max()
    assert d.mean() < 1.0, d.mean()


def test_enhance_flag_on_gather_disp_image():
    from das_diff_veh_tpu.config import DispersionConfig
    from das_diff_veh_tpu.models.vsg import gather_disp_image

    xcf = jnp.asarray(RNG.standard_normal((30, 64)), jnp.float32)
    offs = np.linspace(-150.0, 70.0, 30)
    cfg = DispersionConfig(freq_step=0.5, vel_step=10.0)
    img = gather_disp_image(xcf, offs, 0.004, 8.16, cfg, -150.0, 0.0,
                            enhance=True)
    a = np.asarray(img)
    assert a.dtype == np.int32 and a.min() >= 0 and a.max() <= 255
