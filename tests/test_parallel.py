import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import (DispersionConfig, GatherConfig,
                                     WindowConfig)
from das_diff_veh_tpu.models import vsg as V
from das_diff_veh_tpu.models.vsg import VsgGeometry
from das_diff_veh_tpu.parallel import make_mesh
from das_diff_veh_tpu.parallel.stack import (shard_windows,
                                             sharded_stack_pipeline)
from das_diff_veh_tpu.workloads import make_window_batch


def _tiny_workload(n_windows):
    wcfg = WindowConfig(wlen_sw=2.0, length_sw=120.0)
    gcfg = GatherConfig(wlen=0.5, time_window=1.0)
    dcfg = DispersionConfig(freq_step=0.5, vel_step=20.0)
    batch, x = make_window_batch(n_windows=n_windows, fs=50.0, wcfg=wcfg,
                                 dtype=np.float64)
    g = VsgGeometry.build(x, 1.0 / 50.0, 700.0, 640.0, 730.0, gcfg)
    return batch, x, g, gcfg, dcfg


def test_sharded_stack_matches_single_device():
    assert len(jax.devices()) >= 8, "conftest must fake 8 CPU devices"
    batch, x, g, gcfg, dcfg = _tiny_workload(n_windows=8)
    offs = g.offsets(x)

    # single-device reference
    stack1 = V.stack_gathers(V.build_gather_batch(batch, g, gcfg), batch.valid)
    img1 = V.gather_disp_image(stack1, offs, g.dt, 8.16, dcfg, -60.0, 0.0)

    mesh = make_mesh(8)
    sharded = shard_windows(batch, mesh)
    stack8, img8 = sharded_stack_pipeline(sharded, g, offs, mesh, gcfg, dcfg,
                                          disp_start_x=-60.0, disp_end_x=0.0)
    np.testing.assert_allclose(np.asarray(stack8), np.asarray(stack1),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(img8), np.asarray(img1),
                               rtol=1e-9, atol=1e-12)


def test_sharded_stack_pads_ragged_batch():
    """A window count that doesn't divide the mesh is padded with invalid
    slots and yields the same masked mean."""
    batch, x, g, gcfg, dcfg = _tiny_workload(n_windows=5)
    offs = g.offsets(x)
    stack1 = V.stack_gathers(V.build_gather_batch(batch, g, gcfg), batch.valid)
    mesh = make_mesh(8)
    sharded = shard_windows(batch, mesh)
    assert sharded.data.shape[0] == 8
    stack8, _ = sharded_stack_pipeline(sharded, g, offs, mesh, gcfg, dcfg,
                                       disp_start_x=-60.0, disp_end_x=0.0)
    np.testing.assert_allclose(np.asarray(stack8), np.asarray(stack1),
                               rtol=1e-9, atol=1e-12)


def test_sharded_all_pairs_win_block_streams():
    """Sharded source rows + kernel-grid window streaming compose: ragged
    channel count over the mesh AND a ragged window tail (nwin % win_block
    != 0) must match the unsharded, unstreamed reference."""
    from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(7)
    data = jnp.asarray(rng.standard_normal((26, 1504)).astype(np.float32))
    mesh = make_mesh(8)
    # wlen 64, 50% overlap -> 46 windows; 46 % 8 = 6 ragged tail
    got = np.asarray(sharded_all_pairs_peak(data, 64, mesh, use_pallas=False,
                                            win_block=8, src_chunk=4))
    want = np.asarray(xcorr_all_pairs_peak(data, 64, use_pallas=False))
    assert got.shape == (26, 26)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


# --------------------------------------------------------------------------
# ring pipeline: bit-exact parity + structural no-broadcast pins
# --------------------------------------------------------------------------

def test_ring_all_pairs_bit_exact():
    """The ring path (receiver shards rotating via ppermute) equals the
    unsharded ``xcorr_all_pairs_peak`` BIT-FOR-BIT on the 8-device CPU
    mesh — same style as the parallel/stack.py parity pins.  Covers a
    ragged channel count (26 % 8 != 0: zero-padded rows ride the ring and
    are trimmed), a divisible one, and the 1-device degenerate ring.

    The bit-exact pin runs the KERNEL path (interpret mode): its per-pair
    window accumulation order is fixed by construction, independent of
    shard shape or loop structure.  The einsum fallback's dot_general
    reduction order is lowering-dependent (straight-line vs loop body,
    operand shapes), so it is held to the pre-ring 2e-5 tolerance
    instead."""
    from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(4)
    mesh8 = make_mesh(8)
    for nch in (26, 32):                # ragged and divisible
        data = jnp.asarray(rng.standard_normal((nch, 512)).astype(np.float32))
        want = np.asarray(xcorr_all_pairs_peak(data, 128, use_pallas=True,
                                               interpret=True, src_chunk=4))
        got = np.asarray(sharded_all_pairs_peak(data, 128, mesh8,
                                                use_pallas=True,
                                                interpret=True, src_chunk=4))
        assert got.shape == (nch, nch)
        np.testing.assert_array_equal(got, want)
        got1 = np.asarray(sharded_all_pairs_peak(data, 128, make_mesh(1),
                                                 use_pallas=True,
                                                 interpret=True, src_chunk=4))
        np.testing.assert_array_equal(got1, want)
        # einsum fallback: reduction-order tolerance, not bitwise
        ein = np.asarray(sharded_all_pairs_peak(data, 128, mesh8,
                                                use_pallas=False))
        ein_want = np.asarray(xcorr_all_pairs_peak(data, 128,
                                                   use_pallas=False))
        np.testing.assert_allclose(ein, ein_want, rtol=2e-5, atol=1e-6)


def test_ring_win_block_kernel_bit_exact():
    """Ring + kernel-grid window streaming, bit-exact: the Pallas kernel
    accumulates windows in a fixed static order (unlike the einsum
    fallback, whose dot_general reduction order is shape-dependent), so
    the sharded and unsharded kernels must agree exactly even with a
    ragged window tail AND a ragged channel count."""
    from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(7)
    data = jnp.asarray(rng.standard_normal((26, 1504)).astype(np.float32))
    # wlen 64, 50% overlap -> 46 windows; 46 % 8 = 6 ragged tail
    want = np.asarray(xcorr_all_pairs_peak(data, 64, use_pallas=True,
                                           interpret=True, win_block=8,
                                           src_chunk=4))
    got = np.asarray(sharded_all_pairs_peak(data, 64, make_mesh(8),
                                            use_pallas=True, interpret=True,
                                            win_block=8, src_chunk=4))
    np.testing.assert_array_equal(got, want)


def test_ring_modes_and_buffering_identical():
    """Every RingConfig execution choice is numerics-free on the kernel
    path: replicated vs ring layout and double-buffered vs barrier-
    serialized rotation all produce the identical array (the fixed
    in-kernel accumulation order makes this bitwise, not approximate)."""
    from das_diff_veh_tpu.config import RingConfig
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    rng = np.random.default_rng(9)
    data = jnp.asarray(rng.standard_normal((26, 512)).astype(np.float32))
    mesh = make_mesh(8)
    ref = np.asarray(sharded_all_pairs_peak(data, 128, mesh, use_pallas=True,
                                            interpret=True, src_chunk=4))
    for cfg in (RingConfig(mode="replicated"),
                RingConfig(double_buffer=False)):
        got = np.asarray(sharded_all_pairs_peak(data, 128, mesh,
                                                use_pallas=True,
                                                interpret=True, src_chunk=4,
                                                ring=cfg))
        np.testing.assert_array_equal(got, ref)
    import pytest

    with pytest.raises(ValueError, match="mode"):
        sharded_all_pairs_peak(data, 128, mesh,
                               ring=RingConfig(mode="banana"))


def test_ring_no_receiver_broadcast_jaxpr():
    """Acceptance: the O(nch/D) memory claim is pinned structurally.  The
    traced ring program contains (a) no all-gather / all-to-all, (b) the
    neighbor ppermute (the ring is really there), and (c) no value inside
    the shard_map body shaped like the full receiver spectra set.  The
    replicated layout trips detector (c) by construction, which validates
    the checker itself."""
    from jaxpr_checks import collective_eqns, shard_body_full_set_avals

    from das_diff_veh_tpu.config import RingConfig
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    data = jnp.zeros((26, 512), jnp.float32)   # pads to 32 rows over 8 dev
    mesh = make_mesh(8)
    nch_pad, nwin = 32, (512 - 128) // 64 + 1

    jx = jax.make_jaxpr(
        lambda d: sharded_all_pairs_peak(d, 128, mesh, use_pallas=False)
    )(data)
    assert not collective_eqns(jx), "ring path gathers receiver spectra"
    assert collective_eqns(jx, names=("ppermute",)), "ring rotation missing"
    full = shard_body_full_set_avals(jx, nch_pad, nwin)
    assert not full, f"full receiver set materializes per device: {full}"

    jr = jax.make_jaxpr(
        lambda d: sharded_all_pairs_peak(d, 128, mesh, use_pallas=False,
                                         ring=RingConfig(mode="replicated"))
    )(data)
    assert shard_body_full_set_avals(jr, nch_pad, nwin), \
        "checker failed to flag the replicated layout"


def test_sharded_all_pairs_negative_win_block_rejected():
    import pytest

    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    data = jnp.zeros((8, 256), jnp.float32)
    with pytest.raises(ValueError, match="win_block"):
        sharded_all_pairs_peak(data, 64, make_mesh(8), win_block=-2)


def test_cluster_spec_from_env_conventions():
    """Multi-host bootstrap env parsing: jax-native and torch-style
    conventions, with the jax spelling winning; empty env -> all None
    (falls through to TPU-pod autodetection or single-host no-op)."""
    from das_diff_veh_tpu.parallel import cluster_spec_from_env

    assert cluster_spec_from_env({}) == (None, None, None)
    assert cluster_spec_from_env(
        {"MASTER_ADDR": "10.0.0.1", "MASTER_PORT": "1234",
         "WORLD_SIZE": "4", "RANK": "2"}) == ("10.0.0.1:1234", 4, 2)
    assert cluster_spec_from_env(
        {"MASTER_ADDR": "10.0.0.1", "WORLD_SIZE": "4", "RANK": "0"}
    ) == ("10.0.0.1:8476", 4, 0)
    assert cluster_spec_from_env(
        {"JAX_COORDINATOR_ADDRESS": "c:9", "JAX_NUM_PROCESSES": "2",
         "JAX_PROCESS_ID": "1", "MASTER_ADDR": "ignored",
         "WORLD_SIZE": "8", "RANK": "7"}) == ("c:9", 2, 1)


def test_initialize_cluster_single_host_noop(monkeypatch):
    from das_diff_veh_tpu.parallel import initialize_cluster

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "MASTER_ADDR", "WORLD_SIZE", "RANK",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert initialize_cluster() is False


def test_initialize_cluster_partial_spec_noop(monkeypatch, caplog):
    """A stale MASTER_ADDR without WORLD_SIZE/RANK (partial launcher env)
    must warn and stay single-process, not block on a dead coordinator."""
    import logging

    from das_diff_veh_tpu.parallel import initialize_cluster

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "WORLD_SIZE", "RANK",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.99")
    with caplog.at_level(logging.WARNING):
        assert initialize_cluster() is False
    assert any("incomplete cluster spec" in r.message for r in caplog.records)
