"""Capstone full-stack truth test: known Vs model -> recovered Vs profile.

The reference's entire scientific claim (README.md:1; observed-vs-predicted
closure in inversion_diff_speed.ipynb cells 12-15) in one assertion chain:
a synthetic scene whose dispersive wavefield is computed from a *known*
layered model's own fundamental-mode curve runs through the whole framework

    synthesize -> preprocess/track/select (process_chunk) -> per-window
    virtual shot gathers -> bootstrap dispersion ridges -> curves ->
    differentiable inversion

and the recovered Vs profile must match the model that generated the data.
Every stage is independently parity-tested elsewhere; this test proves they
*compose*.
"""

import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.analysis.bootstrap import bootstrap_disp, sample_indices
from das_diff_veh_tpu.config import (BootstrapConfig, ImagingConfig,
                                     PipelineConfig)
from das_diff_veh_tpu.inversion.curves import curves_from_ridges
from das_diff_veh_tpu.inversion.forward import (LayeredModel,
                                                density_gardner_linear,
                                                phase_velocity,
                                                vp_from_poisson)
from das_diff_veh_tpu.inversion.invert import LayerBounds, ModelSpec, invert
from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section
from das_diff_veh_tpu.models import vsg as V
from das_diff_veh_tpu.pipeline.timelapse import process_chunk


def _truth_model():
    """Soft layer over a stiffer halfspace, fixed Poisson 0.4375 (the speed
    notebooks' nu).  Geometry is chosen so the *observable* band (3.5-10 Hz
    for a 150 m imaging aperture, see below) constrains both parameters:
    the high-frequency plateau c -> 0.92*vs1 is reached by ~8 Hz
    (wavelength < 1.5x layer thickness) and 3.5-4.5 Hz already senses the
    halfspace (wavelength ~ 130 m)."""
    vs = jnp.asarray([0.24, 0.55])
    vp = vp_from_poisson(vs, 0.4375)
    return LayeredModel(thickness=jnp.asarray([0.018, 0.05]), vp=vp, vs=vs,
                        rho=density_gardner_linear(vp))


def test_full_stack_truth_to_vs():
    truth = _truth_model()

    # c(f) lookup for the scene synthesizer: the forward model evaluated on
    # a coarse grid + interpolation (the synthesizer calls it on the full
    # 75k-point rfft axis; c(f) is smooth so 400 points suffice)
    f_grid = np.linspace(0.5, 30.0, 400)
    c_grid = np.asarray(phase_velocity(jnp.asarray(1.0 / f_grid), truth,
                                       mode=0, n_grid=800)) * 1000.0
    assert np.isfinite(c_grid).all()     # fundamental exists everywhere

    def c_of_f(freqs):
        f = np.clip(np.asarray(freqs, float), f_grid[0], f_grid[-1])
        return np.interp(f, f_grid, c_grid)

    # --- scene -> tracked/selected windows -> VSG stack ----------------------
    # same scene scale the e2e ridge test uses (>=5 isolated vehicles)
    scene_cfg = SceneConfig(nch=100, duration=300.0, n_vehicles=8, seed=3,
                            speed_range=(10.0, 20.0), noise_std=0.005,
                            phase_velocity=c_of_f)
    section, _ = synthesize_section(scene_cfg)
    cfg = PipelineConfig().replace(imaging=ImagingConfig(x0=400.0))
    res = process_chunk(section, cfg, method="xcorr")
    assert res.n_windows >= 5

    # --- per-window gathers -> bootstrap ridges ------------------------------
    dt = float(np.asarray(section.t)[1] - np.asarray(section.t)[0])
    g = V.VsgGeometry.build(np.asarray(res.batch.x), dt, cfg.imaging.x0,
                            cfg.imaging.x0 + cfg.imaging.disp_start_x,
                            cfg.imaging.x0 + cfg.gather.far_offset, cfg.gather)
    gathers = V.build_gather_batch(res.batch, g, cfg.gather)
    gathers = jnp.asarray(np.asarray(gathers)[np.asarray(res.batch.valid)])
    n = int(gathers.shape[0])
    # ridge walk anchored at 9.5 Hz (idx 87): the stacked image is sharpest
    # on the high-frequency plateau; sigma=35 m/s per 0.1 Hz step is ~3x the
    # truth curve's steepest slope yet rejects the slant-stack sidelobe
    # branch that appears near 8 Hz.  Band 3.5-10 Hz: below 3.5 Hz the
    # 150 m aperture is under one wavelength, above 10 Hz the 8.16 m
    # channel spacing undersamples (both are physics, not tuning).
    # bt_size = n-3: sample_indices excludes window 0 (reference quirk), so
    # n-1 of the n-1 eligible windows would make every repetition identical
    # — n-3 leaves real resampling spread across the 8 repetitions
    bcfg = BootstrapConfig(bt_times=8, bt_size=n - 3, sigma=(35.0,),
                           ref_freq_idx=(87,), freq_lb=(3.5,), freq_ub=(10.0,))
    idx = sample_indices(n, n - 3, 8, np.random.default_rng(0))
    ridges, freqs = bootstrap_disp(gathers, g.offsets(np.asarray(res.batch.x)),
                                   dt, cfg.interrogator.dx, idx, bcfg,
                                   cfg.dispersion,
                                   disp_start_x=cfg.imaging.disp_start_x,
                                   disp_end_x=cfg.imaging.disp_end_x)
    band = (freqs >= 3.5) & (freqs < 10.0)
    # resampling must produce real spread (distinct reps), yet stay small —
    # the stacked image is stable in the window sample
    spread = ridges[0].std(axis=0)
    assert spread.max() > 0.0
    obs_mean = ridges[0].mean(axis=0)
    med_err = np.median(np.abs(obs_mean - c_of_f(freqs[band]))
                        / c_of_f(freqs[band]))
    assert med_err < 0.08, med_err       # measured 0.017 on this scene

    # --- curves -> inversion -------------------------------------------------
    c = curves_from_ridges(freqs, [3.5], [10.0], [ridges[0]],
                           band_modes=[0])[0]
    # decimate 3x (the parity script's search decimation) and floor the
    # uncertainty at 15 m/s — the bootstrap range measures sampling spread
    # only, not the ~2-4% systematic imaging bias
    cur = c._replace(period=c.period[::3], velocity=c.velocity[::3],
                     uncertainty=np.maximum(c.uncertainty[::3], 1.5e-2))
    spec = ModelSpec(layers=(LayerBounds((0.006, 0.035), (0.15, 0.45)),
                             LayerBounds((0.02, 0.08), (0.35, 0.9))))
    r = invert(spec, [cur], popsize=20, maxiter=60, n_refine_starts=6,
               n_refine_steps=50, n_grid=200, seed=0)

    vs_rec = np.asarray(r.model.vs)
    vs_tru = np.asarray(truth.vs)
    th_rec = float(np.asarray(r.model.thickness)[0])
    # measured on this scene: vs err [0.033, 0.097], thickness 16.2 m vs 18 m
    assert abs(vs_rec[0] - vs_tru[0]) / vs_tru[0] < 0.10, vs_rec
    assert abs(vs_rec[1] - vs_tru[1]) / vs_tru[1] < 0.20, vs_rec
    assert abs(th_rec - 0.018) / 0.018 < 0.40, th_rec
    assert float(r.misfit) < 1.5

    # closure: the recovered model's predicted curve matches the observed
    # ridge (the reference's cell-15 overlay as an assertion)
    pred = np.asarray(phase_velocity(jnp.asarray(cur.period), r.model,
                                     mode=0, n_grid=200))
    assert np.isfinite(pred).all()
    rel = np.abs(pred - cur.velocity) / cur.velocity
    assert np.median(rel) < 0.05, np.median(rel)
