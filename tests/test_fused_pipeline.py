"""Fused single-dispatch chunk pipeline (PR 16): parity vs the staged
oracle, structural zero-host-sync pins, and dispatch accounting.

Parity contract (probed on this backend, documented in docs/PERF.md):
every *structural* field — window count, validity masks, the windowed
data/time/trajectory tensors — is bit-exact between the staged and fused
paths and asserted with ``assert_array_equal``.  The *continuous* outputs
(dispersion image, VSG stack, sub-sample arrival times) are NOT bit-exact:
the staged oracle executes one tiny XLA program per op while the fused
path compiles the whole chunk as one program, and whole-program fusion
reassociates float reductions at the last-ulp level (measured: 1 ulp =
6e-8 on f32 gathers, ~4e-15 relative on the f64 image).  Those fields are
held to a peak-relative 1e-7 oracle bar — seven orders of magnitude of
margin over the measured divergence, and far below the physics assertions
(ridge median error threshold 0.12) that consume the image.

Compile/exec budget: the xcorr parity test runs at the canonical
``pipeline_scene`` geometry (sharing the session fixtures' programs); the
surface_wave parity and both degenerate-chunk tests run on ~3x cheaper
40 s scenes (``small_scene_sw`` and ``small_scene``), whose two fused
programs (xcorr via the echo fixture, surface_wave via the parity
fixture) are likewise traced once per session and reused — the
zero-vehicle test's steady-state counter pins depend on exactly that
reuse.  The full-geometry surface_wave parity is kept under the ``slow``
marker.
"""

import jax
import numpy as np
import pytest

from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.pipeline import fused as F
from das_diff_veh_tpu.pipeline.timelapse import (chunk_body, process_chunk,
                                                 resolve_chunk_metadata)

ORACLE_BAR = 1e-7  # peak-relative; see module docstring


def _peak_rel(got, want) -> float:
    got, want = np.asarray(got), np.asarray(want)
    return float(np.max(np.abs(got - want)) / np.max(np.abs(want)))


# --------------------------------------------------------------------------
# parity vs the staged oracle
# --------------------------------------------------------------------------

def test_fused_xcorr_parity(chunk_result_xcorr, fused_chunk_xcorr):
    s, f = chunk_result_xcorr, fused_chunk_xcorr
    # fused n_windows is a device scalar by design — same value once pulled
    assert int(jax.device_get(f.n_windows)) == s.n_windows >= 1
    assert f.qs_batch is None and s.qs_batch is None

    sb, fb, st, ft = jax.device_get((s.batch, f.batch, s.tracks, f.tracks))
    np.testing.assert_array_equal(fb.valid, sb.valid)
    np.testing.assert_array_equal(fb.data, sb.data)
    np.testing.assert_array_equal(fb.t, sb.t)
    np.testing.assert_array_equal(fb.x, sb.x)
    np.testing.assert_array_equal(fb.traj_x, sb.traj_x)
    np.testing.assert_array_equal(fb.traj_t, sb.traj_t)
    np.testing.assert_array_equal(ft.valid, st.valid)
    np.testing.assert_array_equal(ft.x, st.x)
    np.testing.assert_array_equal(ft.t, st.t)
    # sub-sample arrival times: continuous (Kalman smoother output); the
    # window cut quantizes them away, which is why the batch tensors above
    # stay bit-exact.  Measured divergence 2.4e-4 absolute / 5e-8 relative.
    np.testing.assert_allclose(ft.t_idx, st.t_idx, rtol=1e-6, atol=1e-2,
                               equal_nan=True)

    assert _peak_rel(f.vsg_stack, s.vsg_stack) < ORACLE_BAR    # meas. 2e-16
    assert _peak_rel(f.disp_image, s.disp_image) < ORACLE_BAR  # meas. 4e-15


def test_fused_surface_wave_parity(small_chunk_sw, fused_small_sw):
    s, f = small_chunk_sw, fused_small_sw
    assert int(jax.device_get(f.n_windows)) == s.n_windows >= 1
    assert f.vsg_stack is None and s.vsg_stack is None
    sb, fb = jax.device_get((s.batch, f.batch))
    np.testing.assert_array_equal(fb.valid, sb.valid)
    np.testing.assert_array_equal(fb.data, sb.data)
    assert _peak_rel(f.disp_image, s.disp_image) < ORACLE_BAR


@pytest.mark.slow
def test_fused_surface_wave_parity_full(chunk_result_sw, fused_chunk_sw):
    """Same contract at the canonical full-length geometry (slow: one
    extra full fused surface_wave execution tier-1 doesn't need — the
    small-scene test above pins the same branch)."""
    s, f = chunk_result_sw, fused_chunk_sw
    assert int(jax.device_get(f.n_windows)) == s.n_windows >= 1
    assert f.vsg_stack is None and s.vsg_stack is None
    sb, fb = jax.device_get((s.batch, f.batch))
    np.testing.assert_array_equal(fb.valid, sb.valid)
    np.testing.assert_array_equal(fb.data, sb.data)
    assert _peak_rel(f.disp_image, s.disp_image) < ORACLE_BAR


# --------------------------------------------------------------------------
# degenerate chunks: the on-device masking must survive n_windows == 0
# without a host branch, reusing the already-compiled program
# --------------------------------------------------------------------------

def test_fused_all_invalid_windows(small_scene, fused_small_echo):
    """Superposed close vehicle pair (the echo fixture): tracking still
    finds vehicles, but no isolation window survives (batch.valid all
    False on-device) — the fused program must carry that mask through the
    stack without a host branch."""
    res = fused_small_echo
    n, bvalid, tvalid, img = jax.device_get(
        (res.n_windows, res.batch.valid, res.tracks.valid, res.disp_image))
    assert tvalid.sum() > 0                    # vehicles ARE tracked...
    assert int(n) == 0 and not bvalid.any()    # ...but none is isolated
    assert np.isfinite(img).all()


def test_fused_zero_vehicle_chunk_steady_state(small_scene, fused_cfg,
                                               fused_small_echo):
    """A zero-signal chunk runs through the SAME cached fused program as
    the echo fixture (same geometry, different data -> program-cache hit)
    and comes back with zero windows — and the instrumented run pins the
    dispatch contract: exactly one fused dispatch, zero jaxpr traces,
    zero backend compiles in steady state."""
    from das_diff_veh_tpu.obs import xla_events
    from das_diff_veh_tpu.obs.registry import MetricsRegistry

    section, _ = small_scene
    sec = DasSection(np.zeros_like(np.asarray(section.data)),
                     np.asarray(section.x), np.asarray(section.t))

    reg = MetricsRegistry()
    watch = xla_events.install(reg)
    progs0 = F.n_programs()
    disp0 = F.n_dispatches("process_chunk")
    try:
        res = process_chunk(sec, fused_cfg, method="xcorr")
        n, bvalid, img = jax.device_get(
            (res.n_windows, res.batch.valid, res.disp_image))
    finally:
        xla_events.uninstall(reg)

    assert int(n) == 0 and not bvalid.any()
    assert np.isfinite(img).all()  # masked stack degrades to zeros, not NaN
    assert F.n_programs() == progs0            # program-cache hit
    assert F.n_dispatches("process_chunk") == disp0 + 1
    assert watch.fused_dispatches == 1         # one dispatch per chunk...
    assert watch.traces == 0                   # ...zero steady-state retraces
    assert watch.compiles == 0


# --------------------------------------------------------------------------
# structural pins: zero host syncs inside the fused region, and the
# detector itself is validated by the staged epilogue
# --------------------------------------------------------------------------

def test_fused_body_traces_host_sync_free(pipeline_scene, pipeline_cfg):
    """The fused region proof, per tests/jaxpr_checks.py: (1) ``chunk_body``
    traces to a jaxpr with the data as an abstract value — so no implicit
    device->host coercion exists anywhere inside — and (2) the jaxpr
    contains no callback/infeed primitive that could round-trip at run
    time.  Together: one dispatch in, one pytree out, nothing in between."""
    from jaxpr_checks import host_sync_eqns, trace_or_host_sync

    section, _ = pipeline_scene
    x_dist, t, dt = resolve_chunk_metadata(section, pipeline_cfg)
    aval = jax.ShapeDtypeStruct(np.shape(section.data),
                                np.asarray(section.data).dtype)

    jaxpr = trace_or_host_sync(
        lambda d: chunk_body(d, x_dist, t, dt, pipeline_cfg, method="xcorr"),
        aval)
    assert host_sync_eqns(jaxpr) == []


def test_staged_epilogue_trips_host_sync_detector(pipeline_scene,
                                                  pipeline_cfg):
    """Detector validation: the staged ``process_chunk`` pulls
    ``n_windows`` to a Python int — tracing it as one region must raise
    ``HostSync``.  (This is exactly the sync the fused path removes.)"""
    from jaxpr_checks import HostSync, trace_or_host_sync

    section, _ = pipeline_scene
    x, t = np.asarray(section.x), np.asarray(section.t)
    aval = jax.ShapeDtypeStruct(np.shape(section.data),
                                np.asarray(section.data).dtype)

    with pytest.raises(HostSync):
        trace_or_host_sync(
            lambda d: process_chunk(DasSection(d, x, t), pipeline_cfg),
            aval)


# --------------------------------------------------------------------------
# knob plumbing
# --------------------------------------------------------------------------

def test_chunk_pipeline_knob(pipeline_cfg, fused_cfg):
    from das_diff_veh_tpu.runtime.manifest import config_hash

    # an unknown mode fails loudly before touching any data
    bogus = pipeline_cfg.replace(chunk_pipeline="bogus")
    sec = DasSection(np.zeros((4, 8)), np.arange(4.0), np.arange(8.0) / 250.0)
    with pytest.raises(AssertionError):
        process_chunk(sec, bogus)

    # the knob participates in the runtime config hash: resumed runs and
    # serve bucket caches never silently mix staged and fused programs
    assert (config_hash(pipeline_cfg, "xcorr", False)
            != config_hash(fused_cfg, "xcorr", False))
